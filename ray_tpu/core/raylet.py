"""Raylet: per-node manager — local scheduler, worker pool, object manager.

Equivalent of the reference's raylet process (`src/ray/raylet/node_manager.h`):
the worker lease/dispatch protocol (`HandleRequestWorkerLease`), two-level
scheduling with spillback (`cluster_task_manager.h`, hybrid policy in
`policy/hybrid_scheduling_policy.h`), the worker pool (`worker_pool.h:156`),
dependency management (`dependency_manager.h`), placement-group bundle
2PC resources (`placement_group_resource_manager.h`), and the node's
shared-memory object store + node-to-node transfer (`object_manager.h`).

Differences from the reference, deliberate for the TPU design:
- Tasks are submitted to a raylet and dispatched to workers by the raylet
  (one hop) instead of the lease-then-direct-push protocol; actor calls are
  direct client->worker (matching the reference's direct actor transport).
- TPU chips are node resources; a worker granted TPU resources gets
  `TPU_VISIBLE_CHIPS`/`JAX_PLATFORMS` env so exactly one JAX process per
  host owns the local chips (see SURVEY.md §7 "TPU process model").
- Worker spawning is two-path: a per-node forkserver template (the worker
  forge, core/worker_forge.py) forks fully-imported workers in ~10-20ms
  for fork-compatible grants; cold `exec` spawn remains the fallback and
  the TPU-grant path. See docs/WORKER_POOL.md.
"""

from __future__ import annotations

import json
import logging
import math
import os
import random
import struct
import subprocess
import sys
import threading
import time
import weakref
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import msgpack

from ray_tpu.core import serialization
from ray_tpu.core.common import CPU, TPU, NodeInfo, TaskSpec
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, PlacementGroupID, WorkerID
from ray_tpu.core.object_store import ObjectStoreFullError, SharedMemoryStore
from ray_tpu.core.rpc import (
    DEFERRED,
    Connection,
    ConnectionLost,
    ReconnectingClient,
    RpcClient,
    RpcServer,
)
from ray_tpu.core.worker_forge import ForgeUnavailable, WorkerForge
from ray_tpu.exceptions import RaySystemError
from ray_tpu.jobs.agent import JobAgent
from ray_tpu.jobs.tenancy import JobAdmission
from ray_tpu.observability import tracing as _tracing

logger = logging.getLogger(__name__)


def _marker_preimports(env_extra: Optional[Dict[str, str]]) -> List[str]:
    """The runtime_env `preimports` set riding in a grant's
    RAY_TPU_RUNTIME_ENV marker (runtime_env.granted_env) — what routes a
    spawn to its per-env forge template."""
    marker = (env_extra or {}).get("RAY_TPU_RUNTIME_ENV")
    if not marker:
        return []
    try:
        return list(json.loads(marker).get("preimports") or [])
    except (ValueError, AttributeError):
        return []


# --------------------------------------------------------------------------- #
# Resource accounting
# --------------------------------------------------------------------------- #


class ResourceManager:
    """Local resource ledger (reference `local_resource_manager.h`), including
    dynamically added placement-group bundle resources."""

    def __init__(self, total: Dict[str, float]):
        self._lock = threading.Lock()
        self.total: Dict[str, float] = dict(total)
        self.available: Dict[str, float] = dict(total)
        # Streaming gossip hook (reference ray_syncer.proto: raylets STREAM
        # resource deltas instead of waiting for the heartbeat period):
        # called outside the lock after any ledger change; the raylet wires
        # it to a coalescing delta-push loop.
        self.on_change = None

    def _changed(self):
        cb = self.on_change
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001 — gossip is best-effort
                pass

    def try_acquire(self, request: Dict[str, float]) -> bool:
        with self._lock:
            if all(self.available.get(r, 0.0) + 1e-9 >= amt for r, amt in request.items()):
                for r, amt in request.items():
                    self.available[r] = self.available.get(r, 0.0) - amt
                ok = True
            else:
                ok = False
        if ok:
            self._changed()
        return ok

    def release(self, request: Dict[str, float]):
        with self._lock:
            for r, amt in request.items():
                self.available[r] = self.available.get(r, 0.0) + amt
        self._changed()

    def feasible(self, request: Dict[str, float]) -> bool:
        with self._lock:
            return all(self.total.get(r, 0.0) >= amt for r, amt in request.items())

    def add_resources(self, resources: Dict[str, float]):
        with self._lock:
            for r, amt in resources.items():
                self.total[r] = self.total.get(r, 0.0) + amt
                self.available[r] = self.available.get(r, 0.0) + amt
        self._changed()

    def remove_resources(self, resources: Dict[str, float]):
        with self._lock:
            for r, amt in resources.items():
                self.total[r] = self.total.get(r, 0.0) - amt
                self.available[r] = self.available.get(r, 0.0) - amt
                if abs(self.total[r]) < 1e-9:
                    self.total.pop(r, None)
                    self.available.pop(r, None)
        self._changed()

    def set_total(self, name: str, capacity: float) -> None:
        """Atomically set one resource's TOTAL capacity (dynamic custom
        resources): the read-modify-write must not race concurrent
        bundle add/remove or another set."""
        with self._lock:
            delta = capacity - self.total.get(name, 0.0)
            self.total[name] = self.total.get(name, 0.0) + delta
            self.available[name] = self.available.get(name, 0.0) + delta
            # Delete only when nothing is outstanding: a running task's
            # debt (available < total) must survive a zeroing so its
            # eventual release() can't mint capacity from nowhere.
            if abs(self.total[name]) < 1e-9 \
                    and abs(self.available[name]) < 1e-9:
                self.total.pop(name, None)
                self.available.pop(name, None)
        self._changed()

    def snapshot(self) -> Tuple[Dict[str, float], Dict[str, float]]:
        with self._lock:
            return dict(self.total), dict(self.available)


# --------------------------------------------------------------------------- #
# Worker pool
# --------------------------------------------------------------------------- #


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    pid: int
    conn: Optional[Connection] = None
    # subprocess.Popen for cold spawns, worker_forge._ForgedProc (same
    # poll/wait/terminate/kill surface) for forge forks.
    proc: Optional[Any] = None
    state: str = "starting"          # starting | idle | busy | dead
    # How the process came to be: "forge" (forked from the warm template)
    # or "cold" (exec + full imports).
    spawn_kind: str = "cold"
    # Set when the worker registers its connection — and on death, so
    # spawn-waiters (actor creation) wake on either outcome instead of
    # polling.
    registered: threading.Event = field(default_factory=threading.Event)
    current_task: Optional[TaskSpec] = None
    is_actor: bool = False
    actor_id: Optional[ActorID] = None
    direct_address: Optional[str] = None
    last_idle: float = field(default_factory=time.monotonic)
    # env granted at spawn (e.g. TPU chip visibility)
    granted_env: Dict[str, str] = field(default_factory=dict)
    # Resources held for this worker's lifetime (actor workers hold their
    # creation-task resources until death, like the reference's leases).
    held_resources: Dict[str, float] = field(default_factory=dict)
    # When the current task was dispatched (memory_monitor kills newest
    # first) and, if the OOM killer chose this worker, why.
    task_started: float = 0.0
    oom_kill_reason: Optional[str] = None
    # When mark_dead ran: the reaper prunes long-dead handles from the
    # pool after a grace window (late exit events / by-id lookups still
    # resolve inside it) so worker churn cannot grow the pool forever.
    died_at: float = 0.0


class WorkerPool:
    """Spawns and leases Python worker processes (reference `worker_pool.h`)."""

    def __init__(self, raylet: "Raylet", max_workers: int = 64):
        self._raylet = raylet
        self._lock = threading.RLock()
        self._workers: Dict[WorkerID, WorkerHandle] = {}
        self._starting = 0
        self.max_workers = max_workers
        # Spawn-path accounting (bench/tests assert the forge engages).
        self.spawn_counts: Dict[str, int] = {"forge": 0, "cold": 0}
        # Crash-loop guard: consecutive startup deaths throttle respawns.
        self.consecutive_startup_failures = 0
        self.last_startup_failure = 0.0

    def _spawn_env_delta(self, worker_id: WorkerID,
                         env_extra: Optional[Dict[str, str]]
                         ) -> Dict[str, str]:
        """Worker-specific env on top of this raylet's own environment —
        the full spawn env for a cold exec is os.environ + this delta; a
        forge fork applies ONLY the delta (the template already inherited
        the raylet env at forge start)."""
        delta: Dict[str, str] = {}
        delta.update(GLOBAL_CONFIG.to_env())
        if "RAY_TPU_GRANTED_TPU" not in (env_extra or {}):
            # CPU-only worker: pin jax to CPU so user code touching jax
            # cannot grab chips another process owns. Chip access flows
            # through TPU resource grants only (module docstring "TPU
            # note"). The cold path additionally drops the site-level
            # accelerator-plugin trigger below (a sitecustomize that
            # registers the TPU backend imports jax at interpreter start —
            # ~2s of CPU per spawn); the forge template was started
            # without it.
            delta["JAX_PLATFORMS"] = "cpu"
            delta["RAY_TPU_JAX_PLATFORM"] = "cpu"
        delta.update(env_extra or {})
        # Workers must resolve ray_tpu (and the driver's modules) even when
        # the driver got them via sys.path manipulation rather than an
        # installed package: propagate package root + cwd on PYTHONPATH.
        import ray_tpu as _pkg

        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(_pkg.__file__)))
        extra_paths = [pkg_root, os.getcwd()]
        # A grant-supplied PYTHONPATH (runtime_env env_vars) overrides the
        # raylet's own, exactly as env_extra overrode os.environ in the
        # flat-env spawn — dropping it would lose the user's module roots.
        existing = delta.get("PYTHONPATH") or os.environ.get("PYTHONPATH", "")
        parts = [p for p in extra_paths if p] + ([existing] if existing else [])
        delta["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        delta["RAY_TPU_WORKER_ID"] = worker_id.hex()
        delta["RAY_TPU_RAYLET_ADDRESS"] = self._raylet.server.address
        delta["RAY_TPU_GCS_ADDRESS"] = self._raylet.gcs_address
        delta["RAY_TPU_NODE_ID"] = self._raylet.node_id.hex()
        delta["RAY_TPU_SESSION"] = self._raylet.session_suffix
        delta["RAY_TPU_SESSION_DIR"] = self._raylet.session_dir
        return delta

    def forge_available(self, env_extra: Optional[Dict[str, str]]) -> bool:
        """Would a spawn for this grant take the millisecond fork path?"""
        forge = self._raylet.forge_for(env_extra)
        return (forge is not None and forge.alive
                and WorkerForge.compatible(env_extra or {}))

    def spawn_worker(self, env_extra: Optional[Dict[str, str]] = None,
                     kind: Optional[str] = None) -> WorkerHandle:
        """Start a worker process: forge fork when the template is up and
        the grant is fork-compatible, cold exec otherwise. `kind` pins the
        path ("forge" raises ForgeUnavailable instead of falling back —
        bench/test hook). Never called with the pool or raylet lock held:
        the forge spawn is a socket round trip."""
        worker_id = WorkerID.from_random()
        delta = self._spawn_env_delta(worker_id, env_extra)
        log_dir = os.path.join(self._raylet.session_dir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"worker-{worker_id.hex()[:12]}.out")
        # Register the handle BEFORE the process exists: a forge fork can
        # connect and register within ~10ms, faster than this (possibly
        # GIL-starved) thread gets scheduled again after the spawn reply —
        # a post-spawn insert would make the raylet refuse its own
        # worker's registration.
        handle = WorkerHandle(worker_id=worker_id, pid=0, proc=None,
                              spawn_kind="cold")
        handle.granted_env = env_extra or {}
        spawn_span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            # Roots its own trace (spawns are demand-driven, not tied to
            # one request); the kind attr lands once the path is known.
            spawn_span = _tracing.get_tracer().start_span(
                "worker.spawn",
                attrs={"worker": worker_id.hex()[:12],
                       "node": self._raylet.node_id.hex()[:12]})
        with self._lock:
            self._workers[worker_id] = handle
            self._starting += 1
        # Per-runtime-env routing: a grant carrying preimports forks from
        # its own template (warm module set), everything else from the
        # node-wide default.
        forge = self._raylet.forge_for(env_extra)
        proc = None
        spawn_err: Optional[str] = None
        try:
            if kind != "cold" and forge is not None \
                    and WorkerForge.compatible(env_extra or {}):
                try:
                    proc = forge.spawn(delta, os.getcwd(), log_path)
                    handle.spawn_kind = "forge"
                except ForgeUnavailable as e:
                    if kind == "forge":
                        raise
                    logger.debug("forge spawn unavailable (%s): cold "
                                 "fallback", e)
                    forge.restart_async()
            elif kind == "forge":
                raise ForgeUnavailable(
                    "forge disabled or env fork-incompatible")
            if proc is None:
                env = dict(os.environ)
                if "RAY_TPU_GRANTED_TPU" not in (env_extra or {}):
                    env.pop("PALLAS_AXON_POOL_IPS", None)
                env.update(delta)
                out = open(log_path, "ab")
                proc = subprocess.Popen(
                    [sys.executable, "-u", "-m", "ray_tpu.core.worker"],
                    env=env,
                    stdout=out,
                    stderr=subprocess.STDOUT,
                    cwd=os.getcwd(),
                )
                out.close()  # Popen holds its own dup
        except BaseException as e:
            # No process came to be: unwind the optimistic registration.
            spawn_err = f"{type(e).__name__}: {e}"
            self.mark_dead(worker_id)
            raise
        finally:
            spawn_span.set_attr("kind", handle.spawn_kind)
            spawn_span.end(error=spawn_err)
        handle.pid = proc.pid
        handle.proc = proc
        with self._lock:
            self.spawn_counts[handle.spawn_kind] += 1
        # Event-driven exit detection from birth (satellite of the forge
        # work): cold spawns get a waiter thread; forge forks are covered
        # by the template's exit-event stream.
        self._raylet._watch_worker(handle)
        if proc.poll() is not None and handle.state != "dead":
            # Exit raced the spawn reply (the forge's event stream cannot
            # attribute a pid the pool hadn't seen yet): reap here.
            self._raylet._on_worker_dead(
                handle, f"process exited with code {proc.returncode}")
        return handle

    def on_worker_registered(self, worker_id: WorkerID, conn: Connection,
                             direct_address: str) -> Optional[WorkerHandle]:
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None:
                return None
            handle.conn = conn
            handle.direct_address = direct_address
            if handle.state == "starting":
                self._starting -= 1
                handle.state = "idle"
                handle.last_idle = time.monotonic()
            self.consecutive_startup_failures = 0
        handle.registered.set()
        return handle

    def pop_idle(self, required_env: Optional[Dict[str, str]] = None
                 ) -> Optional[WorkerHandle]:
        """Lease an idle worker whose granted env matches the task's
        requirement (reference worker_pool lease matching): a TPU task must
        run in a worker started with the TPU grant env, and a TPU-granted
        worker must not serve plain CPU tasks."""
        want = required_env or {}
        with self._lock:
            for h in self._workers.values():
                # oom_kill_reason: the memory monitor has condemned this
                # worker; a SIGKILL is in flight — leasing it would get a
                # fresh task killed and blamed with the old task's OOM.
                if (h.state == "idle" and not h.is_actor
                        and h.granted_env == want
                        and not h.oom_kill_reason):
                    h.state = "busy"
                    return h
            return None

    def pop_idle_mismatched(self, want: Dict[str, str]) -> Optional[WorkerHandle]:
        """Longest-idle worker whose granted env does NOT match `want` —
        retired by the dispatcher when the pool is at capacity but no
        env-compatible worker exists (prevents a wedged pool of idle
        workers none of which can serve the queued task)."""
        with self._lock:
            candidates = [h for h in self._workers.values()
                          if h.state == "idle" and not h.is_actor
                          and h.granted_env != want]
            if not candidates:
                return None
            h = min(candidates, key=lambda x: x.last_idle)
            h.state = "busy"  # reserve so nothing else grabs it
            return h

    def push_idle(self, handle: WorkerHandle):
        with self._lock:
            if handle.state != "dead":
                handle.state = "idle"
                handle.current_task = None
                handle.last_idle = time.monotonic()

    def num_starting(self) -> int:
        with self._lock:
            return self._starting

    def num_alive(self, include_actors: bool = True) -> int:
        """Live workers. The pool cap governs *task* workers: dedicated
        actor workers are bounded by their own resource grants, and
        counting them would wedge a node whose pool fills with actors
        (no task worker could ever spawn — reference worker_pool.h keeps
        dedicated workers outside the idle-pool cap)."""
        with self._lock:
            return sum(1 for h in self._workers.values()
                       if h.state != "dead"
                       and (include_actors or not h.is_actor))

    def supply(self, want: Dict[str, str]) -> Tuple[int, int, int]:
        """Worker supply for a grant: (idle leasable workers matching the
        env, starting workers matching the env, live task workers) — the
        inputs of the spawn-ahead deficit computation. Starting workers
        are filtered by grant: an unrelated slow spawn (a TPU worker's
        cold start) must not satisfy THIS grant's demand and suppress its
        spawn. The global starting count (`num_starting`) still governs
        the cold convoy cap."""
        with self._lock:
            idle = sum(1 for h in self._workers.values()
                       if h.state == "idle" and not h.is_actor
                       and h.granted_env == want and not h.oom_kill_reason)
            starting = sum(1 for h in self._workers.values()
                           if h.state == "starting"
                           and h.granted_env == want)
            alive = sum(1 for h in self._workers.values()
                        if h.state != "dead" and not h.is_actor)
            return idle, starting, alive

    def mark_dead(self, worker_id: WorkerID) -> Optional[WorkerHandle]:
        with self._lock:
            handle = self._workers.get(worker_id)
            if handle is None or handle.state == "dead":
                return None
            if handle.state == "starting":
                self._starting -= 1
                self.consecutive_startup_failures += 1
                self.last_startup_failure = time.monotonic()
                if self.consecutive_startup_failures == 3:
                    log_dir = os.path.join(self._raylet.session_dir, "logs")
                    logger.error(
                        "3 consecutive workers died during startup — check "
                        "worker logs in %s. Respawns are throttled to one "
                        "per 5s until a worker starts successfully.", log_dir)
            handle.state = "dead"
            handle.died_at = time.monotonic()
        # Wake spawn-waiters (actor creation) parked on registration.
        handle.registered.set()
        return handle

    def prune_dead(self, grace_s: float = 10.0) -> int:
        """Drop handles that have been dead past the grace window (the
        raylet reaper's anti-entropy call). Without this, worker churn
        grows `_workers` by one dead WorkerHandle — Popen object, env
        dict and all — per spawn, forever (RL011's leak shape)."""
        now = time.monotonic()
        pruned = 0
        with self._lock:
            for wid, h in list(self._workers.items()):
                if h.state == "dead" and h.died_at \
                        and now - h.died_at > grace_s:
                    self._workers.pop(wid, None)
                    pruned += 1
        return pruned

    def spawn_allowed(self) -> bool:
        with self._lock:
            if self.consecutive_startup_failures < 3:
                return True
            return time.monotonic() - self.last_startup_failure > 5.0

    def by_conn(self, conn: Connection) -> Optional[WorkerHandle]:
        wid = conn.meta.get("worker_id")
        if wid is None:
            return None
        with self._lock:
            return self._workers.get(wid)

    def get(self, worker_id: WorkerID) -> Optional[WorkerHandle]:
        with self._lock:
            return self._workers.get(worker_id)

    def kill_all(self):
        with self._lock:
            handles = list(self._workers.values())
        for h in handles:
            if h.proc is not None and h.proc.poll() is None:
                try:
                    h.proc.terminate()
                except OSError:
                    pass  # already reaped
        deadline = time.monotonic() + 3
        for h in handles:
            if h.proc is not None:
                try:
                    h.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    try:
                        h.proc.kill()
                    except OSError:
                        pass  # exited between wait and kill


# --------------------------------------------------------------------------- #
# Queued task bookkeeping
# --------------------------------------------------------------------------- #


# --------------------------------------------------------------------------- #
# Object transfer plane
# --------------------------------------------------------------------------- #
#
# Wire format of the raw `pull_object_chunk` method (raw-bytes RPC framing,
# no pickle on either side):
#   request payload:  msgpack {o: oid bytes, f: offset, l: length,
#                              p: puller node hex}
#   response payload: [4B LE meta length][msgpack meta][chunk bytes]
#     meta: {st: "ok"|"busy"|"missing", s: object size,
#            alt: [node hex, ...] redirect hints, gone: bool}
# The chunk bytes part of an "ok" reply is a memoryview slice of the sealed
# (or in-progress) store segment — the vectored send path writes it to the
# socket without an intermediate copy.

_CHUNK_META_HDR = struct.Struct("<I")


def _pack_chunk_reply(meta: Dict[str, Any], chunk=b"") -> list:
    m = msgpack.packb(meta)
    return [_CHUNK_META_HDR.pack(len(m)), m, chunk]


def _unpack_chunk_reply(raw: bytes) -> Tuple[Dict[str, Any], memoryview]:
    (mlen,) = _CHUNK_META_HDR.unpack_from(raw, 0)
    meta = msgpack.unpackb(raw[4: 4 + mlen])
    return meta, memoryview(raw)[4 + mlen:]


class _ActivePull:
    """Receiver-side state of one in-progress multi-source pull.

    Doubles as the chunk-availability index that lets this node SERVE the
    chunks it has already received while the pull is still running — the
    swarm half of the broadcast plane (a node advertises itself as a
    `partial` location the moment its buffer exists)."""

    __slots__ = ("buf", "size", "chunk_bytes", "lock", "done")

    def __init__(self, buf: memoryview, size: int, chunk_bytes: int):
        self.buf = buf
        self.size = size
        self.chunk_bytes = chunk_bytes
        self.lock = threading.Lock()
        self.done: Set[int] = set()

    def mark_done(self, idx: int):
        with self.lock:
            self.done.add(idx)

    def covers(self, offset: int, length: int) -> bool:
        """True when every chunk overlapping [offset, offset+length) has
        fully landed (the requester's chunk size may differ from ours)."""
        if offset >= self.size:
            return False
        end = min(offset + max(length, 1), self.size)
        first = offset // self.chunk_bytes
        last = (end - 1) // self.chunk_bytes
        with self.lock:
            return all(i in self.done for i in range(first, last + 1))


class _PeerSet:
    """Thread-safe rotating set of source addresses for one pull."""

    # A dropped peer may be re-added (by a directory refresh or redirect
    # hint) after this cool-down — one transient RPC failure must not
    # blacklist a node for the lifetime of a long pull, or a sole
    # surviving holder could become permanently unreachable.
    DROP_COOLDOWN_S = 5.0

    def __init__(self, max_peers: int):
        self._lock = threading.Lock()
        self._addrs: List[str] = []
        self._dead: Dict[str, float] = {}  # addr -> drop time
        self._rr = 0
        self._max = max_peers
        self._last_refresh = 0.0

    def add(self, addr: Optional[str]) -> bool:
        if not addr:
            return False
        with self._lock:
            dropped = self._dead.get(addr)
            if dropped is not None:
                if time.monotonic() - dropped < self.DROP_COOLDOWN_S:
                    return False
                del self._dead[addr]
            if addr in self._addrs or len(self._addrs) >= self._max:
                return False
            self._addrs.append(addr)
            return True

    def drop(self, addr: str):
        with self._lock:
            self._dead[addr] = time.monotonic()
            if addr in self._addrs:
                self._addrs.remove(addr)

    def next(self) -> Optional[str]:
        with self._lock:
            if not self._addrs:
                return None
            self._rr += 1
            return self._addrs[self._rr % len(self._addrs)]

    def snapshot(self) -> List[str]:
        with self._lock:
            return list(self._addrs)

    def may_refresh(self, min_interval_s: float = 0.05) -> bool:
        """Rate-limits directory re-queries across this pull's workers."""
        with self._lock:
            now = time.monotonic()
            if now - self._last_refresh < min_interval_s:
                return False
            self._last_refresh = now
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._addrs)


@dataclass
class QueuedTask:
    spec: TaskSpec
    submitter: Connection
    deps_remaining: Set[ObjectID] = field(default_factory=set)
    queued_at: float = field(default_factory=time.monotonic)
    # Worker-lease request (reference `RequestWorkerLease`,
    # `direct_task_transport.h`): when dispatched, the worker is granted to
    # the submitter for direct task pushes instead of receiving a task.
    lease_req_id: Optional[bytes] = None


# In-process raylet registry (fake clusters / tests / benches run many
# raylets in one process). The same-host attach path consults it for two
# things: resolving a holder's shm session suffix without an RPC, and —
# bench honesty — detecting that the SPECIFIC holder models a network
# link (_chunk_serve_delay_s / _chunk_serve_bw_bps), in which case the
# attach bypass must stand down so link-model numbers stay meaningful.
_LOCAL_RAYLETS: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


class Raylet:
    def __init__(
        self,
        gcs_address: str,
        resources: Dict[str, float],
        session_dir: str,
        session_suffix: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        is_head: bool = False,
        labels: Optional[Dict[str, str]] = None,
        object_store_memory: int = 0,
    ):
        self.node_id = NodeID.from_random()
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.session_suffix = session_suffix or f"{os.getpid()}_{self.node_id.hex()[:8]}"
        self.is_head = is_head
        self.server = RpcServer(host=host, port=port, name="raylet")
        self.server.register_instance(self)
        self.server.on_disconnect = self._on_disconnect
        self.resources = ResourceManager(resources)
        self.store = SharedMemoryStore(
            self.session_suffix,
            capacity_bytes=object_store_memory,
            spill_dir=os.path.join(session_dir, "spill"),
        )
        _LOCAL_RAYLETS[self.node_id.hex()] = self
        cpus = int(resources.get(CPU, 1) or 1)
        self.pool = WorkerPool(self, max_workers=max(4, cpus * 4))
        # CPU workers no longer pay the site-level jax import at spawn
        # (~0.3s, was ~2s — see spawn_worker), so wider spawn bursts stop
        # convoying; still capped to keep small hosts responsive.
        self._spawn_parallelism = max(1, min(4, cpus))
        # Forge forks skip the import bill but each child's runtime INIT
        # is still ~50ms of CPU — an unbounded fork burst convoys those
        # inits and starves everything else on the node, so forge spawns
        # get their own (much wider) cap instead of none.
        self._forge_spawn_parallelism = max(4, cpus * 2)
        self.labels = labels or {}
        self._lock = threading.RLock()
        self._queue: deque[QueuedTask] = deque()
        self._waiting_deps: Dict[ObjectID, List[QueuedTask]] = defaultdict(list)
        self._task_submitters: Dict[bytes, Connection] = {}
        self._running: Dict[bytes, Tuple[TaskSpec, WorkerHandle]] = {}
        self._released_cpu: Dict[bytes, Dict[str, float]] = {}  # blocked-task releases
        self._cluster_view: Dict[str, Any] = {}
        self._spread_rr = 0
        self._pending_actor_creates: Dict[ActorID, Dict[str, Any]] = {}
        self._bundles: Dict[Tuple[bytes, int], Dict[str, Any]] = {}  # (pgid, idx) -> record
        self._pulls_inflight: Set[ObjectID] = set()
        # Transfer plane: in-progress pulls (chunk-availability index — this
        # node serves the chunks it already has), sender-side fairness
        # ledger, and a test/bench hook injecting per-chunk-RPC latency.
        self._active_pulls: Dict[ObjectID, _ActivePull] = {}
        self._outbound_lock = threading.Lock()
        # (oid bytes, puller hex) -> last chunk ts; and -> [distinct
        # offsets served (offset -> bytes; retries count once), last ts]
        # for the coverage ledger (ts drives TTL/eviction so a crashed
        # puller's entry can't exempt it from the gate forever).
        self._outbound_last_seen: Dict[Tuple[bytes, str], float] = {}
        self._outbound_chunks: Dict[
            Tuple[bytes, str], List[Any]] = {}  # [Dict[int, int], float]
        # oid bytes -> {holder hex: ts} — redirect hints, TTL-expired so a
        # holder that later evicts the object stops being advertised.
        self._completed_pullers: Dict[bytes, Dict[str, float]] = {}
        self._chunk_serve_delay_s = 0.0   # sender occupancy per chunk
        self._chunk_fetch_delay_s = 0.0   # per-RPC RTT on the pull side
        # Same-host sealed-segment attach (zero-socket handoff): a pull
        # whose holder shares this host copies the sealed shm segment
        # directly instead of chunking over the wire. Counters feed
        # debug_state and the pull microbench's attach arm.
        self._attach_hits = 0
        self._attach_bytes = 0
        self._chunk_bytes_served = 0      # egress actually sent via RPC
        self._peer_suffix_cache: Dict[str, str] = {}
        # Test/bench link model: when set, ALL chunk egress from this node
        # serializes through one token (a NIC) at this many bytes/s —
        # sleeps, never spins, so the modeled network dominates instead of
        # CPU contention. Models per-host DCN capacity for topology
        # benchmarks (star vs ring collectives); 0 disables.
        self._chunk_serve_bw_bps = 0.0
        self._link_lock = threading.Lock()
        # Sealed replicas whose directory announcement failed (GCS outage
        # mid-pull): re-announced by the heartbeat loop, otherwise the
        # node would stay listed as a stale `partial` location forever.
        self._unannounced_objects: Dict[ObjectID, int] = {}
        # Aborted pulls whose partial-location deregistration is pending:
        # drained by the heartbeat loop, since a lost fire-and-forget
        # remove would advertise this node as a partial holder forever
        # (and keep later pulls of a lost object from fast-aborting).
        self._stale_partials: Set[ObjectID] = set()
        self.server.register_raw("pull_object_chunk", self._serve_chunk_raw)
        # Local clients blocked on an object (event-driven get: the raylet
        # pushes object_ready/object_unavailable instead of clients polling).
        self._object_waiters: Dict[ObjectID, List[Connection]] = defaultdict(list)
        # Non-retryable local pull failures (e.g. object exceeds store
        # capacity): surfaced through get_or_pull instead of endless retry.
        self._pull_errors: Dict[ObjectID, str] = {}
        # Task lifecycle events, flushed to the GCS with the heartbeat.
        # Bounded so a long GCS outage can't grow it without limit (oldest
        # events are the right ones to shed — the GCS ring does the same).
        self._task_event_buffer: deque = deque(
            maxlen=GLOBAL_CONFIG.task_events_max_buffer // 10)
        self._stopped = threading.Event()
        self._dispatch_event = threading.Event()
        # Streaming resource gossip (see _resource_sync_loop).
        self._resources_dirty = threading.Event()
        self._resource_version = 0
        self._peer_resource_versions: Dict[str, int] = {}
        # GCS client with pubsub push handling; reconnects (and re-registers
        # this node + its subscriptions) after a GCS restart — the raylet
        # half of GCS fault tolerance.
        self.gcs = ReconnectingClient(
            gcs_address, name=f"raylet-{self.node_id.hex()[:8]}->gcs",
            push_handler=self._on_gcs_push,
            resubscribe=self._register_with_gcs)
        self._node_info: Optional[NodeInfo] = None
        self._peer_clients: Dict[str, RpcClient] = {}
        self._threads: List[threading.Thread] = []
        # Worker forge (forkserver template) — started in start() when
        # enabled; spawn_worker falls back to cold exec while it is down.
        self.forge: Optional[WorkerForge] = None
        # Job tier (docs/JOBS.md): per-node agent hosting submitted-job
        # driver subprocesses (started in start() when enabled), and the
        # per-job dispatch admission (stride fairness + rate quotas).
        self.job_agent: Optional[JobAgent] = None
        self.job_admission = JobAdmission(
            default_weight=GLOBAL_CONFIG.job_default_tenant_weight)
        # Per-runtime-env forge templates: preimports-csv key ->
        # {"forge": WorkerForge|None, "owners": set}. Owners are job
        # hexes / submission ids; the JOB-channel "finished" event drops
        # refs and the last owner out retires the template — bounded by
        # the set of LIVE jobs with preimports, not job history (RL018).
        self._env_forges: Dict[str, Dict[str, Any]] = {}
        self._env_forges_lock = threading.Lock()
        # Recently finished jobs (job hex -> monotonic ts): the reaper
        # retires their leftover idle workers (ones that were busy when
        # the finished event arrived) and TTL-expires entries, so this
        # tracks a ~60s window of terminations, never all of history
        # (RL018: sweep is _sweep_finished_jobs in the reaper loop).
        self._finished_jobs: Dict[str, float] = {}
        # Per-process waiter threads for cold-spawned workers (event-driven
        # death detection; the 2s reaper loop stays as anti-entropy).
        self._proc_waiters: List[threading.Thread] = []
        self._proc_waiters_lock = threading.Lock()
        # Granted worker leases: lease_id -> {worker, resources, conn}
        # (reference `leased_workers_` in node_manager.h).
        self._leases: Dict[bytes, Dict[str, Any]] = {}

    # ------------------------------------------------------------ lifecycle

    def start(self):
        self.server.start()
        if GLOBAL_CONFIG.worker_forge_enabled:
            try:
                self.forge = WorkerForge(
                    self.session_dir, self.session_suffix,
                    self.node_id.hex(),
                    on_worker_exit=self._on_forge_worker_exit)
                self.forge.start()  # template readies in the background
            except Exception:  # noqa: BLE001 — forge is an optimization
                # e.g. unwritable tmpdir, fork/exec failure: the node must
                # still come up — every spawn just takes the cold path.
                logger.warning("worker forge failed to start; cold spawns "
                               "only", exc_info=True)
                self.forge = None
        if GLOBAL_CONFIG.job_agent_enabled:
            self.job_agent = JobAgent(
                self.node_id.hex(), self.session_dir,
                gcs_call=lambda m, p: self.gcs.call(m, p, timeout=10.0),
                gcs_address=self.gcs_address)
        self._node_info = NodeInfo(
            node_id=self.node_id,
            address=self.server.address,
            object_manager_address=self.server.address,
            session_suffix=self.session_suffix,
            hostname=os.uname().nodename,
            ip=self.server.host,
            resources_total=self.resources.total,
            resources_available=dict(self.resources.total),
            labels=self.labels,
            is_head=self.is_head,
        )
        self._register_with_gcs(self.gcs)
        loops = [
            ("raylet-dispatch", self._dispatch_loop),
            ("raylet-heartbeat", self._heartbeat_loop),
            ("raylet-gcs-sync", self._gcs_sync_loop),
            ("raylet-reaper", self._reaper_loop),
        ]
        if GLOBAL_CONFIG.resource_delta_min_interval_ms > 0:
            # Streaming gossip (reference Ray Syncer): push availability
            # deltas the moment the ledger changes (coalesced) instead of
            # waiting out the heartbeat period — remote schedulers see
            # capacity open up in ~the delta interval, which is what makes
            # spillback decisions fresh under bursty load.
            self.resources.on_change = self._mark_resources_dirty
            loops.append(("raylet-resource-sync", self._resource_sync_loop))
        for name, target in loops:
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        if GLOBAL_CONFIG.memory_monitor_refresh_ms > 0:
            from ray_tpu.core.memory_monitor import MemoryMonitor

            self.memory_monitor = MemoryMonitor(
                self, GLOBAL_CONFIG.memory_monitor_refresh_ms,
                GLOBAL_CONFIG.memory_usage_threshold)
            self.memory_monitor.start()

    def stop(self):
        self._stopped.set()
        if getattr(self, "memory_monitor", None) is not None:
            self.memory_monitor.stop()
        self._dispatch_event.set()
        if self.job_agent is not None:
            # Before kill_all: driver subprocesses get their group kill
            # (and the grace window) while their workers are still being
            # torn down — no orphaned entrypoints outlive the node.
            self.job_agent.shutdown()
        self.pool.kill_all()
        if self.forge is not None:
            # After kill_all (every known worker got its signal first):
            # detach from the shared template — it lingers for the next
            # cluster in this process and self-exits on idle/parent death.
            # An in-flight fork the pool never saw dies on its own when
            # its registration against this stopped raylet fails.
            self.forge.stop()
        with self._env_forges_lock:
            env_forges = [e["forge"] for e in self._env_forges.values()
                          if e["forge"] is not None]
            self._env_forges.clear()
        for f in env_forges:
            f.stop()
        with self._proc_waiters_lock:
            waiters = list(self._proc_waiters)
            self._proc_waiters.clear()
        for t in waiters:
            t.join(timeout=2.0)
        self.server.stop()
        self.gcs.close()
        for c in self._peer_clients.values():
            c.close()
        self.store.shutdown()

    # ------------------------------------------------ worker exit watchers

    def _watch_worker(self, handle: WorkerHandle):
        """Event-driven dead-worker detection: a per-process waiter thread
        for cold spawns (blocked in waitpid, zero-cost until exit); forge
        forks are covered by the template's exit-event stream. Failed
        spawns fail fast instead of waiting out the 2s reaper poll, which
        stays as anti-entropy."""
        if handle.spawn_kind != "cold":
            return
        t = threading.Thread(target=self._proc_waiter, args=(handle,),
                             name=f"worker-wait-{handle.pid}", daemon=True)
        with self._proc_waiters_lock:
            self._proc_waiters = [x for x in self._proc_waiters
                                  if x.is_alive()]
            self._proc_waiters.append(t)
        t.start()

    def _proc_waiter(self, handle: WorkerHandle):
        try:
            handle.proc.wait()
        except Exception:  # noqa: BLE001 — proc already reaped elsewhere
            return
        if self._stopped.is_set() or handle.state == "dead":
            return
        self._on_worker_dead(
            handle, f"process exited with code {handle.proc.returncode}")

    def _on_forge_worker_exit(self, pid: int, code: int):
        """Forge exit-event stream: a forked worker died (its waitpid
        lives in the template process)."""
        if self._stopped.is_set():
            return
        with self.pool._lock:
            handle = next((h for h in self.pool._workers.values()
                           if h.pid == pid and h.state != "dead"), None)
        if handle is not None:
            self._on_worker_dead(handle,
                                 f"process exited with code {code}")

    def _register_with_gcs(self, client):
        """Announce this node and (re)establish its subscriptions. Called at
        startup and again by the reconnecting client after a GCS restart.

        `reconcile_actors` asks the GCS to cross-check the actors it
        believes ALIVE here against what this node actually hosts (via a
        fresh `list_live_actors` query): actor-death reports sent during
        a GCS outage are lost, and a restored ghost address would
        otherwise make every caller error against it until a minutes-long
        timeout."""
        client.call("register_node", {
            "info": self._node_info,
            "reconcile_actors": True,
            # Reconcile list for the job table: RUNNING jobs the GCS
            # believes live here but a restarted agent doesn't know are
            # failed instead of hanging forever.
            "running_jobs": (self.job_agent.running()
                             if self.job_agent is not None else []),
        })
        client.call("subscribe", {"channel": "RESOURCES", "key": b"*"})
        client.call("subscribe", {"channel": "OBJECT", "key": b"*"})
        client.call("subscribe", {"channel": "JOB", "key": b"*"})

    def handle_list_live_actors(self, conn: Connection, data=None):
        """Actors this node currently hosts OR is creating right now —
        the GCS's failover reconciliation compares its restored table
        against this (in-flight creations count as hosted: failing one
        over would kill an actor that is coming up this instant)."""
        with self.pool._lock:
            live = {h.actor_id for h in self.pool._workers.values()
                    if h.is_actor and h.actor_id is not None
                    and h.state != "dead"}
        with self._lock:
            live.update(self._pending_actor_creates.keys())
        return {"actors": list(live)}

    # ------------------------------------------------------------- job tier

    def handle_agent_run_job(self, conn: Connection, data: Dict[str, Any]):
        """GCS -> agent: launch a submitted job's driver on this node."""
        if self.job_agent is None:
            raise RuntimeError("job agent disabled on this node")
        self.job_agent.run_job(data["submission_id"], data["entrypoint"],
                               data.get("runtime_env"))
        return {"ok": True}

    def handle_agent_stop_job(self, conn: Connection, data: Dict[str, Any]):
        stopped = False
        if self.job_agent is not None:
            stopped = self.job_agent.stop_job(data["submission_id"])
        return {"stopped": stopped}

    def _on_job_event(self, msg: Dict[str, Any]):
        """JOB-channel pubsub from the GCS — the raylet side of the job
        lifecycle: seed admission + pre-warm forges at the front, reclaim
        workers/forges/admission entries at the back."""
        event = msg.get("event")
        if event == "submitted":
            # Submission-time pre-warm: the per-env template pays its
            # preimport bill WHILE the driver subprocess is still
            # starting, so the job's first task forks instead of cold-
            # spawning (bench_jobs measures exactly this overlap).
            renv = msg.get("runtime_env") or {}
            if GLOBAL_CONFIG.job_prewarm_forge and renv.get("preimports"):
                self._env_forge_for(renv["preimports"],
                                    owner=msg.get("submission_id", ""))
        elif event == "running":
            job_hex = msg.get("job_id") or ""
            if job_hex:
                self.job_admission.register(job_hex, msg.get("tenant_qos"))
            renv = msg.get("runtime_env") or {}
            if renv.get("preimports"):
                self._env_forge_for(renv["preimports"], owner=job_hex)
        elif event == "finished":
            job_hex = msg.get("job_id") or ""
            sid = msg.get("submission_id") or ""
            if job_hex:
                self.job_admission.unregister(job_hex)
                with self._lock:
                    self._finished_jobs[job_hex] = time.monotonic()
                self._reclaim_job_workers(job_hex)
            self._release_env_forges({o for o in (job_hex, sid) if o})
            self._dispatch_event.set()

    def _reclaim_job_workers(self, job_hex: str):
        """Retire idle workers whose granted env belongs to a finished
        job: their runtime_env (working_dir, env_vars, preimports) died
        with the job, so no future task can ever lease them — left
        alone they'd sit as permanent orphans against the pool cap."""
        with self.pool._lock:
            victims = [h for h in self.pool._workers.values()
                       if h.state == "idle" and not h.is_actor
                       and h.granted_env.get("RAY_TPU_JOB_ID") == job_hex]
            for h in victims:
                h.state = "busy"  # reserve so dispatch can't lease them
        for h in victims:
            self._on_worker_dead(h, "job finished")
            if h.proc is not None and h.proc.poll() is None:
                try:
                    h.proc.terminate()
                except OSError:
                    pass  # already reaped

    _FINISHED_JOB_TTL_S = 60.0

    def _sweep_finished_jobs(self):
        """Reaper-loop anti-entropy for job cleanup: workers that were
        BUSY when the finished event arrived go idle a moment later and
        would dodge the event-time reclaim; re-sweeping for the TTL
        window catches them. Expiry bounds the dict (RL018)."""
        now = time.monotonic()
        with self._lock:
            if not self._finished_jobs:
                return
            for jh in [j for j, ts in self._finished_jobs.items()
                       if now - ts > self._FINISHED_JOB_TTL_S]:
                del self._finished_jobs[jh]
            live = list(self._finished_jobs)
        for job_hex in live:
            self._reclaim_job_workers(job_hex)

    def forge_for(self, env_extra: Optional[Dict[str, str]]
                  ) -> Optional[WorkerForge]:
        """The forge template serving this grant: the node-wide default
        unless the runtime_env carries `preimports`, in which case a
        per-env template (grown on demand, refcounted by owning job)."""
        pre = _marker_preimports(env_extra)
        if not pre:
            return self.forge
        if not GLOBAL_CONFIG.worker_forge_enabled:
            return None
        return self._env_forge_for(
            pre, owner=(env_extra or {}).get("RAY_TPU_JOB_ID", ""))

    def _env_forge_for(self, preimports: List[str], owner: str
                       ) -> Optional[WorkerForge]:
        """Get-or-create the template for this preimport set and add
        `owner`'s ref. Launch happens OUTSIDE the lock (RL002: template
        exec is a fork/exec); racers see forge=None while it launches
        and cold-spawn — only the very first spawns pay that."""
        base = [m.strip() for m in
                GLOBAL_CONFIG.worker_forge_preimports.split(",") if m.strip()]
        extra = [m for m in preimports if m and m not in base]
        key = ",".join(base + extra)
        with self._env_forges_lock:
            ent = self._env_forges.get(key)
            creator = ent is None
            if creator:
                ent = self._env_forges[key] = {"forge": None, "owners": set()}
            if owner:
                ent["owners"].add(owner)
            if not creator:
                return ent["forge"]
        forge: Optional[WorkerForge] = None
        try:
            forge = WorkerForge(
                self.session_dir, self.session_suffix, self.node_id.hex(),
                on_worker_exit=self._on_forge_worker_exit, preimports=key)
            forge.start()
        except Exception:  # noqa: BLE001 — per-env forge is an optimization
            logger.warning("per-env forge failed to start; cold spawns for "
                           "runtime_env preimports=%s", key, exc_info=True)
            forge = None
        with self._env_forges_lock:
            ent["forge"] = forge
        return forge

    def _release_env_forges(self, dead_owners: Set[str]):
        if not dead_owners:
            return
        to_stop = []
        with self._env_forges_lock:
            for key, ent in list(self._env_forges.items()):
                ent["owners"] -= dead_owners
                if not ent["owners"]:
                    del self._env_forges[key]
                    if ent["forge"] is not None:
                        to_stop.append(ent["forge"])
        for f in to_stop:
            # Detach only: the shared template lingers briefly and
            # self-exits on idle, so a resubmitted job re-warms cheaply.
            f.stop()

    def _pending_demand(self, cap: int = 64) -> List[Dict[str, float]]:
        """Resource shapes of queued tasks that can't run right now — the
        autoscaler's scale-up signal (reference ResourceDemandScheduler
        input)."""
        with self._lock:
            shapes = []
            for qt in self._queue:
                if len(shapes) >= cap:
                    break
                if not qt.deps_remaining and qt.spec.resources:
                    shapes.append(dict(qt.spec.resources))
            return shapes

    def _mark_resources_dirty(self):
        self._resources_dirty.set()

    def _resource_sync_loop(self):
        """Streamed availability deltas to the GCS (reference
        `ray_syncer.proto` RaySyncer streams; heartbeats remain the
        periodic anti-entropy full report). Coalesces bursts: at most one
        delta per resource_delta_min_interval_ms."""
        interval = GLOBAL_CONFIG.resource_delta_min_interval_ms / 1000.0
        while not self._stopped.is_set():
            if not self._resources_dirty.wait(timeout=1.0):
                continue
            if self._stopped.is_set():
                return
            time.sleep(interval)  # coalesce the burst behind one delta
            self._resources_dirty.clear()
            total, avail = self.resources.snapshot()
            self._resource_version += 1
            try:
                self.gcs.call_async(
                    "resource_delta",
                    {"node_id": self.node_id,
                     "resources_available": avail,
                     "resources_total": total,
                     "version": self._resource_version})
            except Exception:  # noqa: BLE001 — heartbeat is the backstop
                pass

    def _heartbeat_loop(self):
        """Pure liveness beat. Anything slow (task-event flush, object
        re-announcements) lives in _gcs_sync_loop: sharing this loop with
        a 5s-timeout flush once delayed the next beat past the GCS health
        window during create storms — a false node death under load."""
        period = GLOBAL_CONFIG.raylet_heartbeat_period_ms / 1000.0
        while not self._stopped.wait(period):
            try:
                # Version BEFORE snapshot: a resource delta racing this
                # heartbeat snapshots after its version bump, so whichever
                # state is fresher always carries the strictly newer
                # version — snapshotting first could pair an old snapshot
                # with the delta's new version and silently revert it.
                version = self._resource_version
                total, avail = self.resources.snapshot()
                resp = self.gcs.call(
                    "heartbeat",
                    {"node_id": self.node_id, "resources_available": avail,
                     "resources_total": total,
                     "resource_version": version,
                     "pending_demand": self._pending_demand()},
                    timeout=5,
                )
                if not resp.get("registered"):
                    # A GCS that restarted without persisted node state (or
                    # that marked us dead during the outage): re-announce.
                    self._register_with_gcs(self.gcs)
            except Exception:
                if self._stopped.is_set():
                    return
                logger.warning("heartbeat to GCS failed", exc_info=True)

    def _gcs_sync_loop(self):
        """Anti-entropy GCS sync (split off the heartbeat loop so its
        bounded-but-slow RPCs can never delay a liveness beat): failed
        object announcements, stale partial-location removals, and the
        task-event flush."""
        period = GLOBAL_CONFIG.raylet_heartbeat_period_ms / 1000.0
        while not self._stopped.wait(period):
            try:
                with self._lock:
                    unannounced = list(self._unannounced_objects.items())
                    self._unannounced_objects.clear()
                for i, (oid, size) in enumerate(unannounced):
                    if not self.store.contains(oid):
                        continue
                    try:
                        self.gcs.call(
                            "object_location_add",
                            {"object_id": oid, "node_id": self.node_id,
                             "size": size}, timeout=5)
                    except Exception:  # noqa: BLE001 — retry next beat
                        # First failure: re-queue the REST and stop — N
                        # sequential 5s timeouts against a flaky GCS would
                        # stall this thread past the node-death threshold.
                        with self._lock:
                            for o, s in unannounced[i:]:
                                self._unannounced_objects[o] = s
                        break
                with self._lock:
                    stale = list(self._stale_partials)
                for oid in stale:
                    if self.store.contains(oid):
                        with self._lock:
                            self._stale_partials.discard(oid)
                        continue  # re-pulled since: now a real location
                    try:
                        self.gcs.call(
                            "object_location_remove",
                            {"object_id": oid, "node_id": self.node_id,
                             "partial": True}, timeout=5)
                        with self._lock:
                            self._stale_partials.discard(oid)
                    except Exception:  # noqa: BLE001 — retry next beat,
                        break          # same stall rationale as above
                # Bounded flush batches: after a 20k-task storm a raylet
                # holds tens of thousands of buffered events, and one
                # giant pickled add_task_events monopolizes the (shared,
                # GIL-bound) control plane for seconds right when the
                # next phase's work needs it. The deque sheds oldest on
                # overflow, so draining over several beats loses nothing.
                with self._lock:
                    events = [self._task_event_buffer.popleft()
                              for _ in range(min(
                                  2000, len(self._task_event_buffer)))]
                if events:
                    try:
                        self.gcs.call("add_task_events", {"events": events},
                                      timeout=5)
                    except Exception:
                        # Flush failed (e.g. GCS mid-restart): keep the
                        # events for the next attempt instead of losing
                        # this window's spans.
                        with self._lock:
                            self._task_event_buffer.extendleft(
                                reversed(events))
                        raise
            except Exception:
                if self._stopped.is_set():
                    return
                logger.warning("GCS sync failed", exc_info=True)

    def _reaper_loop(self):
        # Reap idle workers beyond the prestart target and poll dead processes.
        while not self._stopped.wait(2.0):
            with self.pool._lock:
                handles = list(self.pool._workers.values())
            for h in handles:
                if h.proc is not None and h.proc.poll() is not None and h.state != "dead":
                    self._on_worker_dead(h, f"process exited with code {h.proc.returncode}")
            # Long-dead handles leave the pool after a grace window so
            # worker churn cannot grow it without bound.
            self.pool.prune_dead()
            self._sweep_finished_jobs()

    # ------------------------------------------------------- GCS push events

    def _on_gcs_push(self, method: str, data: Any):
        if method == "pubsub_batch":
            # Delta-batched frame: the GCS coalesced this subscriber's
            # OBJECT/RESOURCES events behind one push (order per key
            # preserved; `seq` strictly increases per connection).
            for ev in data.get("events", ()):
                self._on_gcs_push("pubsub", ev)
            return
        if method != "pubsub":
            return
        channel = data["channel"]
        if channel == "RESOURCES":
            msg = data["message"]
            if "delta" in msg:
                # Streamed per-node delta: merge, dropping stale versions
                # (deltas and full views race; versions are per-node
                # monotonic). Heartbeat full views are the anti-entropy.
                view = dict(self._cluster_view)
                for node_hex, entry in msg["delta"].items():
                    ver = entry.get("version", 0)
                    if ver and ver < self._peer_resource_versions.get(
                            node_hex, 0):
                        continue
                    if ver:
                        # Pruned by the full-view anti-entropy below:
                        # each heartbeat view rebuild drops versions for
                        # nodes outside the live set, so dead peers
                        # cannot accumulate (and node ids are never
                        # reused — a stale guard cannot reject a
                        # replacement node's gossip).
                        # raylint: disable=RL012 — swept by full view
                        self._peer_resource_versions[node_hex] = ver
                    view[node_hex] = entry
                self._cluster_view = view
            else:
                self._cluster_view = msg
                # Full view is the anti-entropy: drop version state for
                # nodes that left the cluster (autoscaler churn would
                # otherwise grow this dict one entry per dead node).
                self._peer_resource_versions = {
                    k: v for k, v in self._peer_resource_versions.items()
                    if k in msg}
            # New capacity may have appeared (autoscaler launch): queued
            # tasks this node can never run get handed back to their
            # submitters for re-routing (reference task spilling).
            self._respill_infeasible()
        elif channel == "JOB":
            self._on_job_event(data["message"])
        elif channel == "OBJECT":
            oid = ObjectID(data["key"])
            with self._lock:
                has_waiters = (oid in self._waiting_deps
                               or oid in self._pulls_inflight
                               or oid in self._object_waiters)
            if has_waiters:
                entry = data["message"]
                if entry.get("inline") is not None:
                    self._on_object_local(oid)
                elif entry.get("nodes"):
                    self._start_pull(oid)

    # --------------------------------------------------- submission path

    def handle_submit_task(self, conn: Connection, data: Dict[str, Any]):
        spec: TaskSpec = data["spec"]
        grant_or_reject = data.get("grant_or_reject", False)
        target = self._choose_node(spec)
        if target is not None and target != self.node_id.hex() and not grant_or_reject:
            addr = self._cluster_view.get(target, {}).get("address")
            if addr:
                return {"status": "spillback", "address": addr}
        self._enqueue(spec, conn)
        return {"status": "queued"}

    def handle_request_worker_lease(self, conn: Connection, data: Dict[str, Any]):
        """Grant a worker to the caller for direct task pushes (reference
        `NodeManager::HandleRequestWorkerLease`, node_manager.cc). The
        request queues like a task; the grant arrives as a `lease_granted`
        push once a worker + resources are available."""
        spec: TaskSpec = data["spec"]
        grant_or_reject = data.get("grant_or_reject", False)
        target = self._choose_node(spec)
        if target is not None and target != self.node_id.hex() and not grant_or_reject:
            addr = self._cluster_view.get(target, {}).get("address")
            if addr:
                return {"status": "spillback", "address": addr}
        qt = QueuedTask(spec=spec, submitter=conn,
                        lease_req_id=data["req_id"])
        with self._lock:
            self._queue.append(qt)
        self._dispatch_event.set()
        return {"status": "pending"}

    def handle_cancel_lease_request(self, conn: Connection, data: Dict[str, Any]):
        """Owner no longer needs a queued worker lease (demand drained) —
        reference `CancelWorkerLease` (node_manager.cc). Queued requests
        are dropped; already-granted ones are returned by the owner."""
        req_ids = set(data["req_ids"])
        with self._lock:
            doomed = [qt for qt in self._queue
                      if qt.lease_req_id is not None
                      and qt.lease_req_id in req_ids]
            for qt in doomed:
                self._queue.remove(qt)
        return {"cancelled": len(doomed)}

    def handle_return_worker_lease(self, conn: Connection, data: Dict[str, Any]):
        lease_id: bytes = data["lease_id"]
        with self._lock:
            lease = self._leases.pop(lease_id, None)
        if lease is None:
            return {"returned": False}
        worker: WorkerHandle = lease["worker"]
        # Exactly-once via the held_resources swap: a concurrent worker
        # death releases through the same helper and whoever swaps first
        # wins (releasing lease["resources"] directly would double-free).
        self._release_held_resources(worker)
        if worker.state != "dead":
            self.pool.push_idle(worker)
        self._dispatch_event.set()
        return {"returned": True}

    def _grant_lease(self, worker: WorkerHandle, qt: QueuedTask):
        """Worker + resources acquired for a lease request: hand the worker
        to the requester over its push channel."""
        lease_id = os.urandom(16)
        worker.held_resources = dict(qt.spec.resources)
        with self._lock:
            self._leases[lease_id] = {
                "worker": worker, "resources": dict(qt.spec.resources),
                "conn": qt.submitter,
            }
        try:
            qt.submitter.push("lease_granted", {
                "req_id": qt.lease_req_id, "lease_id": lease_id,
                "address": worker.direct_address,
                "raylet_address": self.server.address,
                "node_id": self.node_id,
                "worker_id": worker.worker_id,
            })
        except Exception:  # noqa: BLE001 — requester gone: unwind the grant
            with self._lock:
                self._leases.pop(lease_id, None)
            self._release_held_resources(worker)
            self.pool.push_idle(worker)

    def _reclaim_conn_leases(self, conn: Connection):
        """A lease holder disconnected: its workers may be running orphaned
        tasks — kill them (reference: leased workers are destroyed when the
        owner dies, node_manager.cc HandleUnexpectedWorkerFailure)."""
        with self._lock:
            doomed = [(lid, l) for lid, l in self._leases.items()
                      if l["conn"] is conn]
            for lid, _ in doomed:
                self._leases.pop(lid, None)
        for _, lease in doomed:
            worker: WorkerHandle = lease["worker"]
            # held_resources carries the lease grant; _on_worker_dead
            # releases it exactly once.
            self._on_worker_dead(worker, "lease holder disconnected")
            if worker.proc is not None and worker.proc.poll() is None:
                try:
                    worker.proc.terminate()
                except Exception:  # noqa: BLE001
                    pass
        if doomed:
            self._dispatch_event.set()

    def handle_direct_task_event(self, conn: Connection, data: Dict[str, Any]):
        """Task lifecycle events for directly-executed tasks, reported by
        the worker (the raylet never sees these tasks' dispatch)."""
        with self._lock:
            for ev in data["events"]:
                self._task_event_buffer.append(ev)
        return {}

    def _choose_node(self, spec: TaskSpec) -> Optional[str]:
        """Hybrid scheduling policy over the gossiped cluster view
        (reference `policy/hybrid_scheduling_policy.h`): prefer local while
        utilization is under threshold, else the best feasible node."""
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
            SpreadSchedulingStrategy,
        )

        strategy = spec.scheduling_strategy
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            return strategy.node_id
        view = self._cluster_view
        if not view:
            return None  # no view yet: keep it local
        req = spec.resources
        my_hex = self.node_id.hex()

        def available_now(entry):
            return all(entry["available"].get(r, 0.0) + 1e-9 >= a for r, a in req.items())

        def feasible(entry):
            return all(entry["total"].get(r, 0.0) >= a for r, a in req.items())

        feasible_nodes = [nid for nid, e in view.items() if e.get("alive") and feasible(e)]
        if isinstance(strategy, SpreadSchedulingStrategy):
            if not feasible_nodes:
                return None
            self._spread_rr += 1
            ordered = sorted(feasible_nodes)
            return ordered[self._spread_rr % len(ordered)]
        local = view.get(my_hex)
        # Data locality (reference `lease_policy.h:56` LocalityAwareLeasePolicy):
        # a task consuming large resident objects runs where the bytes are
        # instead of pulling them across the network. Locality outranks
        # INSTANTANEOUS availability: with streamed resource gossip the
        # view is fresh enough to see a node busy for the few ms a cached
        # lease or finishing task still holds its CPU, and bouncing the
        # task off-data to "ready" nodes costs a multi-MB pull — feasible
        # is enough, the data node queues it for the next free worker.
        # Feasible-only locality needs the streamed-gossip freshness
        # argument above; with gossip disabled (heartbeat-only views, up
        # to a full period stale) a merely-feasible data node may be
        # saturated for seconds, so fall back to requiring available-now.
        gossip_on = GLOBAL_CONFIG.resource_delta_min_interval_ms > 0

        def locality_ok(entry):
            return feasible(entry) and (gossip_on or available_now(entry))

        best_data = self._best_data_node(spec)
        if best_data == my_hex and local is not None and locality_ok(local):
            return my_hex  # the bytes are HERE: keep it, don't bounce
        if best_data is not None and best_data != my_hex:
            entry = view.get(best_data)
            if entry is not None and entry.get("alive") and locality_ok(entry):
                return best_data
        if local is not None and feasible(local) and available_now(local):
            return my_hex
        ready = [nid for nid in feasible_nodes if available_now(view[nid])]
        if ready:
            # Prefer local even when queued work exists? No: pick the
            # most-available ready node for work stealing across the cluster.
            ready.sort(key=lambda nid: -sum(view[nid]["available"].values()))
            return ready[0]
        if local is not None and feasible(local):
            return my_hex  # queue locally until resources free up
        if feasible_nodes:
            return feasible_nodes[0]
        return my_hex if local is not None else None

    # Below this, pulling is cheap enough that resource-based placement wins.
    _LOCALITY_MIN_BYTES = 1 << 20

    def _best_data_node(self, spec: TaskSpec) -> Optional[str]:
        """Node holding the most resident bytes of the task's ref args, or
        None when deps are absent/small/inline/local. One batched GCS
        lookup, only paid by dep-carrying tasks whose bytes are NOT
        already here (the common fast paths never leave the process).
        NEVER call while holding self._lock — the GCS round trip would
        stall every other handler on the node."""
        deps = spec.dependencies()
        if not deps:
            return None
        if all(self.store.contains(d) for d in deps):
            # Everything resident here: no RPC needed. Large bytes anchor
            # the task to this node (see _choose_node); small ones don't.
            if sum(self.store.local_size(d)
                   for d in deps) >= self._LOCALITY_MIN_BYTES:
                return self.node_id.hex()
            return None
        try:
            entries = self.gcs.call("object_locations_batch",
                                    {"object_ids": deps}, timeout=5)["entries"]
        except Exception:  # noqa: BLE001 — locality is advisory
            return None
        per_node: Dict[str, float] = {}
        for e in entries:
            if not e.get("known") or e.get("has_inline"):
                continue
            size = e.get("size", 0)
            if size < self._LOCALITY_MIN_BYTES:
                continue
            for nid in e.get("nodes", ()):
                key = nid.hex() if hasattr(nid, "hex") else str(nid)
                per_node[key] = per_node.get(key, 0) + size
        if not per_node:
            return None
        best = max(per_node, key=per_node.get)
        return best if per_node[best] >= self._LOCALITY_MIN_BYTES else None

    def _enqueue(self, spec: TaskSpec, submitter: Connection):
        qt = QueuedTask(spec=spec, submitter=submitter)
        with self._lock:
            self._task_submitters[spec.task_id.binary()] = submitter
            for dep in spec.dependencies():
                if not self._dep_available(dep):
                    qt.deps_remaining.add(dep)
                    self._waiting_deps[dep].append(qt)
            self._queue.append(qt)
        for dep in list(qt.deps_remaining):
            self._start_pull(dep)
        self._dispatch_event.set()

    def _respill_infeasible(self):
        """Queued tasks whose resources exceed this node's totals can only
        run elsewhere; once the cluster view shows a node that fits, return
        them to their submitter for re-routing (it re-runs the normal
        submit path, which spills to the capable node)."""
        with self._lock:
            snapshot = [qt for qt in self._queue
                        if not qt.deps_remaining
                        and not self.resources.feasible(qt.spec.resources)]
        # _choose_node may consult the GCS (data locality): keep it OUTSIDE
        # the lock — a slow GCS must not freeze dispatch for the node.
        candidates = []
        for qt in snapshot:
            target = self._choose_node(qt.spec)
            if target is not None and target != self.node_id.hex():
                candidates.append(qt)
        with self._lock:
            candidates = [qt for qt in candidates if qt in self._queue]
            for qt in candidates:
                self._queue.remove(qt)
                self._task_submitters.pop(qt.spec.task_id.binary(), None)
        for qt in candidates:
            if qt.submitter is not None and qt.submitter.alive:
                try:
                    qt.submitter.push("task_respill", {"spec": qt.spec})
                    continue
                except Exception:  # noqa: BLE001
                    pass
            logger.warning("dropping respilled task %s (submitter gone)",
                           qt.spec.name)

    def _dep_available(self, oid: ObjectID) -> bool:
        if self.store.contains(oid):
            return True
        try:
            entry = self.gcs.call("object_locations_get", {"object_id": oid}, timeout=5)
        except Exception:  # noqa: BLE001 — unreachable GCS == not available
            logger.debug("object_locations_get for %s failed", oid,
                         exc_info=True)
            return False
        return bool(entry.get("known") and entry.get("inline") is not None)

    # ------------------------------------------------------- dispatch loop

    def _dispatch_loop(self):
        while not self._stopped.is_set():
            self._dispatch_event.wait(timeout=0.2)
            self._dispatch_event.clear()
            try:
                self._dispatch_once()
            except Exception:
                logger.exception("dispatch loop error")

    # Dispatch policy (reference picks from a scored top-k rather than
    # strict FIFO, `hybrid_scheduling_policy.h:61`): scan past tasks whose
    # resources aren't available right now, so an infeasible or busy head
    # never wedges the node. Anti-starvation: once a *feasible* task has
    # waited past the aging threshold, nothing younger may jump it — the
    # node drains until its resources fit. Never-feasible tasks (requests
    # exceeding node total) can't age-block since they can't drain-to-fit.
    _DISPATCH_SCAN_LIMIT = 128
    _DISPATCH_AGING_S = 10.0

    def _dispatch_once(self):
        progressed = True
        while progressed and not self._stopped.is_set():
            progressed = False
            with self._lock:
                now = time.monotonic()
                # Group the dep-free scan window by job (FIFO preserved
                # within each job); the slot is then offered to jobs in
                # stride order, so a weight-8 job's task storm cannot
                # monopolize dispatch over a weight-1 job's trickle.
                # With a single job this degrades to exactly the old
                # FIFO scan.
                by_job: Dict[str, List[int]] = {}
                scanned = 0
                for i, qt in enumerate(self._queue):
                    if qt.deps_remaining:
                        continue
                    by_job.setdefault(qt.spec.job_id.hex(), []).append(i)
                    if (now - qt.queued_at > self._DISPATCH_AGING_S
                            and self.resources.feasible(qt.spec.resources)):
                        # Aged feasible task: reserve — nothing younger
                        # (in ANY job) may jump it; the node drains
                        # until its resources fit.
                        for jh in by_job:
                            by_job[jh] = [x for x in by_job[jh] if x <= i]
                        break
                    scanned += 1
                    if scanned >= self._DISPATCH_SCAN_LIMIT:
                        break
                ready_idx = None
                for jh in self.job_admission.order(list(by_job)):
                    # Token-bucket rate quota: a throttled job's tasks
                    # stay queued (the 0.2s dispatch tick retries);
                    # other jobs' candidates still get the slot.
                    if self.job_admission.admit(jh) > 0.0:
                        continue
                    for i in by_job[jh]:
                        qt = self._queue[i]
                        if self.resources.try_acquire(qt.spec.resources):
                            ready_idx = i
                            break
                    if ready_idx is not None:
                        break
                    # Nothing dispatchable for this job right now: give
                    # back the stride/bucket charge it didn't use.
                    self.job_admission.refund(jh)
                if ready_idx is None:
                    return
                qt = self._queue[ready_idx]
                del self._queue[ready_idx]
            env = self._env_for(qt.spec)
            worker = self.pool.pop_idle(env)
            if worker is None:
                # Spawn-ahead: size the spawn burst to the queued demand
                # for this grant (this task + dep-free queue head), so a
                # task burst pipelines its worker starts instead of
                # trickling one spawn per dispatch pass.
                with self._lock:
                    pending_specs = [q2.spec for q2 in self._queue
                                     if not q2.deps_remaining]
                    del pending_specs[self._DISPATCH_SCAN_LIMIT:]
                demand = 1 + sum(1 for s in pending_specs
                                 if self._env_for(s) == env)
                self._spawn_for_demand(env, demand)
                # keep resources held? No: release and retry when a worker registers.
                self.resources.release(qt.spec.resources)
                with self._lock:
                    self._queue.appendleft(qt)
                return
            if qt.lease_req_id is not None:
                if qt.submitter is None or not qt.submitter.alive:
                    # Requester died while the lease waited in queue.
                    self.resources.release(qt.spec.resources)
                    self.pool.push_idle(worker)
                else:
                    self._grant_lease(worker, qt)
            else:
                self._dispatch_to(worker, qt)
            progressed = True

    def _spawn_for_demand(self, env: Dict[str, str], demand: int):
        """Spawn-ahead hysteresis: bring (idle + starting) worker supply
        for this grant up to the queued demand. Spawn-kind-aware — forge
        forks skip the import bill so they get the wide
        `_forge_spawn_parallelism` cap; cold exec spawns keep the tight
        `_spawn_parallelism` cap (parallel interpreter starts are CPU
        bound and convoy on small hosts; pool size still targets the
        node's CPU count, reference worker_pool.h:347 prestarts one
        worker per core). Starting workers count as supply, so bursts
        never over-spawn past demand, and the caps pace a burst to the
        node instead of convoying every child's runtime init at once."""
        while not self._stopped.is_set():
            idle, starting, alive = self.pool.supply(env)
            if alive >= self.pool.max_workers:
                # Pool full of env-incompatible workers: retire one so a
                # compatible worker can be spawned on the next pass.
                stale = self.pool.pop_idle_mismatched(env)
                if stale is None:
                    return
                self._on_worker_dead(stale, "retired (env mismatch)")
                if stale.proc is not None and stale.proc.poll() is None:
                    try:
                        stale.proc.terminate()
                    except OSError:
                        pass  # already reaped
                continue
            if idle + starting >= demand or not self.pool.spawn_allowed():
                return
            # The convoy cap is GLOBAL (every starting interpreter shares
            # the node's cores), while the deficit above is per-grant.
            cap = self._forge_spawn_parallelism \
                if self.pool.forge_available(env) else self._spawn_parallelism
            if self.pool.num_starting() >= cap:
                return
            self.pool.spawn_worker(env_extra=env)

    def _env_for(self, spec: TaskSpec) -> Dict[str, str]:
        env: Dict[str, str] = {}
        tpus = spec.resources.get(TPU, 0)
        if tpus:
            env["RAY_TPU_GRANTED_TPU"] = str(tpus)
        # runtime_env (reference runtime_env system): workers are leased
        # by matching granted env, so tasks with different env_vars or
        # working_dir/py_modules get different worker processes; the
        # worker materializes URI packages at startup.
        renv = spec.runtime_env or {}
        for k, v in (renv.get("env_vars") or {}).items():
            env[str(k)] = str(v)
        if renv.get("working_dir") or renv.get("py_modules") \
                or renv.get("pip") or renv.get("preimports"):
            from ray_tpu.core import runtime_env as renv_mod

            env.update(renv_mod.granted_env(renv))
        # Job-scoped worker isolation: the job id is part of the granted
        # env, so pop_idle's exact match never hands one job's worker
        # (its env_vars, working_dir, preimported modules) to another
        # job's task, and job-finish reclamation can find every worker
        # the job left behind by this tag.
        env["RAY_TPU_JOB_ID"] = spec.job_id.hex()
        return env

    def _dispatch_to(self, worker: WorkerHandle, qt: QueuedTask):
        spec = qt.spec
        worker.current_task = spec
        worker.task_started = time.monotonic()
        if _tracing._ENABLED:
            # Queue-time span, reconstructed at dispatch: a child of the
            # task's span so "where did the latency go" shows raylet
            # queueing separately from execution.
            now = time.monotonic()
            _tracing.get_tracer().record_span(
                "raylet.queue", _tracing.epoch_of(qt.queued_at),
                _tracing.epoch_of(now), parent_ctx=spec.trace_ctx,
                attrs={"task": spec.name,
                       "node": self.node_id.hex()[:12]})
        with self._lock:
            self._running[spec.task_id.binary()] = (spec, worker)
        self._record_task_event(spec, "RUNNING", worker)
        try:
            worker.conn.push("execute_task", {"spec": spec})
        except (ConnectionLost, OSError):
            self._on_worker_dead(worker, "push failed")

    def _record_task_event(self, spec: TaskSpec, state: str,
                           worker: Optional[WorkerHandle] = None):
        """Task lifecycle event for the state API / chrome timeline
        (reference gcs_task_manager events); buffered, flushed with the
        heartbeat so the hot path never waits on the GCS."""
        with self._lock:
            self._task_event_buffer.append({
                "task_id": spec.task_id.hex(),
                "name": spec.name,
                "state": state,
                "ts": time.time(),
                "node_id": self.node_id.hex()[:12],
                "worker_id": worker.worker_id.hex()[:12] if worker else None,
                "pid": worker.pid if worker else None,
                "queued_at": spec.submitted_at,
                **(spec.trace_ctx or {}),
            })

    # --------------------------------------------- worker-facing handlers

    def handle_register_worker(self, conn: Connection, data: Dict[str, Any]):
        worker_id: WorkerID = data["worker_id"]
        conn.meta["worker_id"] = worker_id
        handle = self.pool.on_worker_registered(worker_id, conn, data.get("direct_address"))
        if handle is None:
            # Worker not spawned by us (e.g. driver-embedded runtime): ignore.
            return {"ok": False}
        self._dispatch_event.set()
        return {"ok": True, "node_id": self.node_id, "session_suffix": self.session_suffix}

    def handle_task_done(self, conn: Connection, data: Dict[str, Any]):
        """Worker finished a task: register results, notify submitter, recycle."""
        task_id_b: bytes = data["task_id"].binary()
        results: List[Dict[str, Any]] = data.get("results", [])
        error_blob: Optional[bytes] = data.get("error")
        with self._lock:
            entry = self._running.pop(task_id_b, None)
            submitter = self._task_submitters.pop(task_id_b, None)
            released = self._released_cpu.pop(task_id_b, None)
        if entry is None:
            return {}
        spec, worker = entry
        self._record_task_event(
            spec, "FAILED" if error_blob is not None else "FINISHED", worker)
        # Resource release (handle partial release from blocked state).
        acquired = self._acquired_resources(spec)
        if released:
            for r, amt in released.items():
                acquired[r] = acquired.get(r, 0) - amt
        remaining = {r: a for r, a in acquired.items() if a > 0}
        if spec.actor_creation and error_blob is None:
            # The actor's *lifetime* resources stay held until death/kill
            # (reference: the lease stays acquired); the placement-only
            # surplus (default 1 CPU used to schedule creation) is returned.
            lifetime = {r: a for r, a in spec.resources.items() if a > 0}
            worker.held_resources = lifetime
            surplus = {r: a - lifetime.get(r, 0.0) for r, a in remaining.items()
                       if a - lifetime.get(r, 0.0) > 0}
            self.resources.release(surplus)
        else:
            self.resources.release(remaining)
        self._register_results(spec, results)
        if submitter is not None and submitter.alive:
            try:
                submitter.push("task_result",
                               {"task_id": spec.task_id, "results": results,
                                "error": error_blob})
            except (ConnectionLost, OSError):
                logger.debug("task_result push for %s dropped: submitter "
                             "gone", spec.task_id, exc_info=True)
        if spec.actor_creation:
            # Dedicated actor worker: stays busy serving direct calls.
            # Resolve by the REPORTING WORKER, not by actor id alone: a
            # superseded create attempt (GCS failover race) shares the
            # actor id AND task id with the live attempt, and resolving
            # the newer record with the older attempt's outcome handed
            # callers a worker the newer attempt never created.
            with self._lock:
                pending = self._pending_actor_creates.get(spec.actor_id)
                ours = pending is not None and pending.get("worker") is worker
                if ours:
                    self._pending_actor_creates.pop(spec.actor_id, None)
            if ours:
                pending["result"] = {"error": error_blob, "worker": worker}
                pending["event"].set()
            else:
                # A superseded attempt's worker finished its creation
                # late: it must not linger as a second host of the actor
                # (its eventual death would also be misattributed to the
                # live incarnation) — kill it silently.
                logger.info("terminating superseded duplicate actor "
                            "worker pid=%s for %s", worker.pid,
                            spec.actor_id.hex()[:12])
                worker.is_actor = False  # suppress the actor_died report
                if self.pool.mark_dead(worker.worker_id) is not None:
                    self._release_held_resources(worker)
                if worker.proc is not None and worker.proc.poll() is None:
                    try:
                        worker.proc.terminate()
                    except OSError:
                        pass  # already reaped
        else:
            self.pool.push_idle(worker)
        self._dispatch_event.set()
        return {}

    def _register_results(self, spec: TaskSpec, results: List[Dict[str, Any]]):
        for r in results:
            oid: ObjectID = r["object_id"]
            if r["kind"] == "inline":
                try:
                    # Pipelined: the submitter gets results directly via the
                    # task_result push; the directory entry only serves
                    # later cross-node dependents, so the completion path
                    # need not wait a GCS round trip per task.
                    self.gcs.call_async(
                        "object_location_add",
                        {"object_id": oid, "inline": r["data"],
                         "size": len(r["data"]), "owner": spec.owner_address})
                except Exception:
                    logger.warning("failed to register inline object %s", oid)
            else:  # sealed into the node store by the worker
                try:
                    self.store.adopt(oid, r["size"])
                except Exception:
                    logger.warning("failed to adopt %s", oid, exc_info=True)
                try:
                    self.gcs.call("object_location_add",
                                  {"object_id": oid, "node_id": self.node_id,
                                   "size": r["size"], "owner": spec.owner_address},
                                  timeout=10)
                except Exception:  # noqa: BLE001 — GCS down; gossip repairs
                    logger.debug("object_location_add for %s failed", oid,
                                 exc_info=True)
                self._on_object_local(oid)

    def handle_object_sealed(self, conn: Connection, data: Dict[str, Any]):
        """A local process (driver/worker put) sealed a segment directly."""
        oid: ObjectID = data["object_id"]
        self.store.adopt(oid, data["size"])
        self.gcs.call("object_location_add",
                      {"object_id": oid, "node_id": self.node_id, "size": data["size"],
                       "owner": data.get("owner")}, timeout=10)
        self._on_object_local(oid)
        return {}

    @staticmethod
    def _acquired_resources(spec: TaskSpec) -> Dict[str, float]:
        """What the raylet actually acquired for this task (actor creation
        acquires placement_resources, everything else spec.resources)."""
        if spec.actor_creation:
            return dict(spec.placement_resources or spec.resources)
        return dict(spec.resources)

    def handle_worker_blocked(self, conn: Connection, data: Dict[str, Any]):
        """Worker blocked in get(): release its CPU so others can run
        (reference: raylet marks the lease as blocked and can start more)."""
        handle = self.pool.by_conn(conn)
        if handle is None or handle.current_task is None:
            return {}
        spec = handle.current_task
        cpus = self._acquired_resources(spec).get(CPU, 0)
        if cpus:
            with self._lock:
                self._released_cpu[spec.task_id.binary()] = {CPU: cpus}
            self.resources.release({CPU: cpus})
            self._dispatch_event.set()
        return {}

    def handle_worker_unblocked(self, conn: Connection, data: Dict[str, Any]):
        handle = self.pool.by_conn(conn)
        if handle is None or handle.current_task is None:
            return {}
        spec = handle.current_task
        with self._lock:
            released = self._released_cpu.pop(spec.task_id.binary(), None)
        if released:
            # Oversubscribe rather than deadlock: force re-acquire.
            with self.resources._lock:
                for r, amt in released.items():
                    self.resources.available[r] = self.resources.available.get(r, 0) - amt
        return {}

    def _release_held_resources(self, handle: WorkerHandle):
        """Release lifetime-held (actor) resources exactly once per worker."""
        held, handle.held_resources = handle.held_resources, {}
        if held:
            self.resources.release(held)

    def _on_worker_dead(self, handle: WorkerHandle, reason: str):
        handle = self.pool.mark_dead(handle.worker_id)
        if handle is None:
            return
        with self._lock:
            # A leased worker dying invalidates its lease record (a late
            # return_worker_lease must not double-release the resources —
            # held_resources below releases them exactly once).
            stale = [lid for lid, l in self._leases.items()
                     if l["worker"] is handle]
            for lid in stale:
                self._leases.pop(lid, None)
        self._release_held_resources(handle)
        logger.warning("worker %s (pid %s) died: %s", handle.worker_id.hex()[:12],
                       handle.pid, reason)
        try:
            # Release any object borrows the dead worker held (the owner's
            # pending frees would otherwise leak store bytes forever).
            self.gcs.call_async("borrower_gone",
                                {"borrower_id": handle.worker_id.hex()})
        except Exception:  # noqa: BLE001
            pass
        spec = handle.current_task
        if spec is not None:
            task_id_b = spec.task_id.binary()
            with self._lock:
                self._running.pop(task_id_b, None)
                submitter = self._task_submitters.pop(task_id_b, None)
                released = self._released_cpu.pop(task_id_b, None)
            res = self._acquired_resources(spec)
            if released:  # worker was blocked in get(): CPU already released
                for r, amt in released.items():
                    res[r] = res.get(r, 0) - amt
            self.resources.release({r: a for r, a in res.items() if a > 0})
            if handle.is_actor or spec.actor_creation:
                pass  # reported below via actor_died
            elif submitter is not None and submitter.alive:
                from ray_tpu.exceptions import (
                    OutOfMemoryError,
                    WorkerCrashedError,
                )

                if handle.oom_kill_reason:
                    exc: WorkerCrashedError = OutOfMemoryError(
                        f"Task {spec.name} was killed by the memory "
                        f"monitor: {handle.oom_kill_reason}")
                else:
                    exc = WorkerCrashedError(
                        f"Worker died while running {spec.name}: {reason}")
                err = serialization.serialize_exception(exc, spec.name)
                try:
                    submitter.push("task_result",
                                   {"task_id": spec.task_id, "results": [],
                                    "error": err, "crashed": True})
                except (ConnectionLost, OSError):
                    logger.debug("crash report for %s dropped: submitter "
                                 "gone", spec.task_id, exc_info=True)
        if handle.is_actor and handle.actor_id is not None:
            with self._lock:
                pending = self._pending_actor_creates.get(handle.actor_id)
                superseded = (pending is not None
                              and pending.get("worker") is not handle)
                if pending is not None and not superseded:
                    self._pending_actor_creates.pop(handle.actor_id, None)
                else:
                    pending = None  # a newer attempt owns the record
            if pending is not None:
                pending["result"] = {"error": serialization.serialize_exception(
                    RaySystemError(f"actor worker died during creation: {reason}"))}
                pending["event"].set()
            if superseded:
                # This worker belonged to a SUPERSEDED create attempt: a
                # newer attempt owns the actor's record, so reporting
                # actor_died here would burn a restart of (or terminally
                # kill) the live incarnation that is coming up right now.
                logger.info("suppressing actor_died for %s: worker pid=%s "
                            "was a superseded create attempt's",
                            handle.actor_id.hex()[:12], handle.pid)
            else:
                try:
                    self.gcs.call("actor_died",
                                  {"actor_id": handle.actor_id,
                                   "reason": reason,
                                   "intended": False}, timeout=5)
                except Exception:  # noqa: BLE001 — GCS death detection
                    logger.debug("actor_died report for %s failed",
                                 handle.actor_id, exc_info=True)
            # actor resources released on death
            if handle.current_task is None and handle.actor_id is not None:
                pass
        self._dispatch_event.set()

    def _on_disconnect(self, conn: Connection):
        handle = self.pool.by_conn(conn)
        if handle is not None and handle.state != "dead":
            self._on_worker_dead(handle, "connection lost")
        self._reclaim_conn_leases(conn)
        # Submitter connections: drop pending notification targets.
        with self._lock:
            doomed = [t for t, c in self._task_submitters.items() if c is conn]
            for t in doomed:
                del self._task_submitters[t]
            for oid in list(self._object_waiters):
                ws = self._object_waiters[oid]
                if conn in ws:
                    ws.remove(conn)
                    if not ws:
                        del self._object_waiters[oid]

    # ------------------------------------------------------ actor creation

    def _pop_pending_create_if_ours(self, actor_id, pending) -> None:
        """Drop an actor's pending-create record only when it is still
        THIS attempt's — an unconditional pop would tear down a newer
        (superseding) attempt's record and strand its waiter."""
        with self._lock:
            if self._pending_actor_creates.get(actor_id) is pending:
                self._pending_actor_creates.pop(actor_id, None)

    def handle_create_actor(self, conn: Connection, data: Dict[str, Any]):
        """GCS asks this node to host an actor (reference
        `GcsActorScheduler::LeaseWorkerFromNode`)."""
        spec: TaskSpec = data["spec"]
        placement = spec.placement_resources or spec.resources
        if not self.resources.try_acquire(placement):
            return {"status": "retry"}
        env = self._env_for(spec)
        # Reuse an idle pooled worker whose granted env matches (reference
        # worker_pool.h lease matching) — saves a cold start.
        worker = self.pool.pop_idle(env)
        if worker is None:
            worker = self.pool.spawn_worker(env_extra=env)
        worker.is_actor = True
        worker.actor_id = spec.actor_id
        pending = {"event": threading.Event(), "result": None, "env": env,
                   "worker": worker}
        # One pending record per actor, owned by the NEWEST attempt.
        # Concurrent creates for the same actor are real under GCS
        # failover: the dead incarnation's create RPC keeps running on
        # this raylet while the restarted GCS re-kicks its own. The older
        # attempt is superseded — fired with an error now (its caller is
        # gone or will retry) — and completions resolve records by the
        # WORKER that reported, never by actor id alone (the creation
        # spec, and so its task id, is identical across attempts).
        with self._lock:
            prev = self._pending_actor_creates.pop(spec.actor_id, None)
            self._pending_actor_creates[spec.actor_id] = pending
        if prev is not None:
            logger.warning(
                "create_actor for %s superseded an in-flight attempt "
                "(GCS failover re-kick racing the old incarnation)",
                spec.actor_id.hex()[:12])
            prev["result"] = {"error": serialization.serialize_exception(
                RaySystemError("superseded by a newer create attempt"))}
            prev["event"].set()
        # Spawn-ahead hysteresis for create bursts: in-flight creates on
        # this node (each arrives on its own GCS connection) are queued
        # demand — prespawn so the next creates find registered idle
        # workers instead of serializing their own starts. Only creates
        # with the SAME grant count: a prespawned worker can serve only
        # an env-matching create.
        with self._lock:
            inflight = sum(1 for p in self._pending_actor_creates.values()
                           if p.get("env") == env)
        if inflight > 1:
            self._spawn_for_demand(env, inflight - 1)
        # Wait for registration (event-driven: `registered` is set on
        # register AND on death — no 10ms poll; the 0.5s slice is pure
        # anti-entropy against a lost event).
        deadline = time.monotonic() + GLOBAL_CONFIG.worker_lease_timeout_ms / 1000.0
        while worker.conn is None and worker.state != "dead":
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            worker.registered.wait(min(remaining, 0.5))
        if worker.conn is None:
            self.resources.release(placement)
            self._pop_pending_create_if_ours(spec.actor_id, pending)
            if worker.state == "dead" or (worker.proc is not None
                                          and worker.proc.poll() is not None):
                return {"status": "error",
                        "error": f"actor worker exited at startup "
                                 f"(code {worker.proc.returncode})"}
            return {"status": "error", "error": "actor worker failed to register"}
        worker.state = "busy"
        qt = QueuedTask(spec=spec, submitter=conn)
        with self._lock:
            self._task_submitters[spec.task_id.binary()] = conn
        self._dispatch_to(worker, qt)
        if not pending["event"].wait(GLOBAL_CONFIG.worker_lease_timeout_ms / 1000.0):
            # Hung __init__: kill the worker; _on_worker_dead releases the
            # resources and cleans up the pending record. Pop only OUR
            # record — a newer attempt may have superseded it.
            self._pop_pending_create_if_ours(spec.actor_id, pending)
            if worker.proc is not None and worker.proc.poll() is None:
                try:
                    worker.proc.terminate()
                except OSError:
                    pass  # already reaped
            return {"status": "error", "error": "actor creation timed out"}
        result = pending["result"]
        if result.get("error") is not None:
            # Creation-task resources were already released by task_done (or
            # by _on_worker_dead if the worker died) — don't double-release.
            return {"status": "error", "error": "actor __init__ raised",
                    "error_blob": result["error"]}
        worker.current_task = None  # stays busy (dedicated), serving direct calls
        return {"status": "ok", "worker_id": worker.worker_id,
                "direct_address": worker.direct_address}

    def handle_kill_worker(self, conn: Connection, data: Dict[str, Any]):
        handle = self.pool.get(data["worker_id"])
        if handle is None:
            return {}
        if data.get("suppress_report", True):
            # GCS marks the actor dead itself (kill with no_restart); a
            # restartable kill must still report actor_died so the GCS
            # drives the RESTARTING transition.
            handle.is_actor = False
        if self.pool.mark_dead(handle.worker_id) is not None:
            self._release_held_resources(handle)
        if handle.proc is not None and handle.proc.poll() is None:
            try:
                handle.proc.terminate()
            except OSError:
                pass  # already reaped
        elif handle.proc is None and handle.conn is not None:
            handle.conn.close()
        if handle.is_actor and handle.actor_id is not None:
            try:
                self.gcs.call("actor_died",
                              {"actor_id": handle.actor_id,
                               "reason": data.get("reason", "killed"),
                               "intended": False}, timeout=5)
            except Exception:  # noqa: BLE001 — GCS death detection covers it
                logger.debug("actor_died report for %s failed",
                             handle.actor_id, exc_info=True)
        return {}

    # ---------------------------------------------------------- chaos hooks

    def handle_chaos_kill_worker(self, conn: Connection, data: Dict[str, Any]):
        """Fault injection (ray_tpu/chaos): SIGKILL one live worker
        PROCESS on this node — no graceful path, no actor bookkeeping.
        Death is discovered by the normal exit-event / reaper machinery
        exactly as a real crash would be, which is the point: the chaos
        plane must exercise detection, not shortcut it. `draw` picks the
        victim deterministically from the sorted live set; `actors_only`
        restricts to dedicated actor workers."""
        import signal as _signal

        draw = int(data.get("draw", 0))
        actors_only = bool(data.get("actors_only", False))
        with self.pool._lock:
            victims = sorted(
                (h for h in self.pool._workers.values()
                 if h.state != "dead" and h.pid
                 and h.proc is not None and h.proc.poll() is None
                 and (h.is_actor or not actors_only)),
                key=lambda h: h.worker_id.hex())
        if not victims:
            return {"killed": False}
        victim = victims[draw % len(victims)]
        try:
            os.kill(victim.pid, _signal.SIGKILL)
        except OSError as e:
            return {"killed": False, "error": str(e)}
        logger.warning("chaos: SIGKILLed worker pid=%d (%s, actor=%s)",
                       victim.pid, victim.worker_id.hex()[:12],
                       victim.is_actor)
        return {"killed": True, "pid": victim.pid,
                "worker_id": victim.worker_id.hex(),
                "actor": victim.is_actor}

    def handle_chaos_kill_forge(self, conn: Connection, data: Dict[str, Any]):
        """Fault injection: SIGKILL the worker-forge template process.
        The forge client notices the loss, restarts the template in the
        background, and spawns fall back to cold exec meanwhile (the
        PR-5 failover discipline this injector exists to exercise)."""
        import signal as _signal

        forge = self.forge
        proc = forge.proc if forge is not None else None
        if proc is None or proc.poll() is not None:
            return {"killed": False}
        try:
            os.kill(proc.pid, _signal.SIGKILL)
        except OSError as e:
            return {"killed": False, "error": str(e)}
        logger.warning("chaos: SIGKILLed forge template pid=%d", proc.pid)
        return {"killed": True, "pid": proc.pid}

    # ------------------------------------------------------ object transfer

    def _start_pull(self, oid: ObjectID):
        with self._lock:
            if oid in self._pulls_inflight or self.store.contains(oid):
                return
            self._pulls_inflight.add(oid)
        threading.Thread(target=self._pull_worker, args=(oid,), daemon=True).start()

    def _pull_worker(self, oid: ObjectID):
        try:
            entry = self.gcs.call("object_locations_get", {"object_id": oid}, timeout=10)
            if not entry.get("known"):
                with self._lock:
                    self._pulls_inflight.discard(oid)
                return  # OBJECT pubsub push will re-trigger when it appears
            if entry.get("inline") is not None:
                with self._lock:
                    self._pulls_inflight.discard(oid)
                self._on_object_local(oid)
                return
            my_hex = self.node_id.hex()
            if any(n.hex() == my_hex for n in entry.get("nodes", [])):
                with self._lock:
                    self._pulls_inflight.discard(oid)
                self._on_object_local(oid)
                return
            ok = False
            try:
                ok = self._pull_object_pipelined(oid, entry)
            except Exception:  # noqa: BLE001 — includes ObjectStoreFullError
                logger.warning("pull of %s failed", oid, exc_info=True)
            with self._lock:
                self._pulls_inflight.discard(oid)
            if ok:
                self._on_object_local(oid)
            else:
                # Every advertised location failed (or there were none):
                # wake blocked owners so they can reconstruct, not hang.
                self._notify_object_waiters(oid, "object_unavailable")
                # Tasks parked on this dependency would wait forever (no
                # object_ready will ever fire): run the lost-dep ladder —
                # tell owners to reconstruct, re-pull with bounded
                # backoff while they do, and only then fail the parked
                # tasks with a loss-shaped error (the PR-10 watchdog
                # class: bounded recovery, never a hang).
                self._handle_lost_dep(oid)
        except Exception:
            with self._lock:
                self._pulls_inflight.discard(oid)
            logger.exception("pull worker failed for %s", oid)

    # Lost-dep ladder bound: ~5s of re-pull attempts while the owner's
    # reconstruction runs, then parked tasks fail loss-shaped.
    _LOST_DEP_RETRIES = 10
    _LOST_DEP_BACKOFF_S = 0.5

    def _handle_lost_dep(self, oid: ObjectID, attempt: int = 0):
        """A dependency pull found no live locations. Ladder:

        1. notify each parked task's submitter (`task_dep_lost`) — the
           OWNER holds the creating task's spec and re-executes it;
        2. re-check the directory with bounded backoff, restarting the
           pull the moment the re-executed object registers;
        3. after the bound, complete the still-parked tasks with an
           ObjectLostError result (loss-shaped, so data-plane lineage
           can recompute) — a fault becomes a bounded error, not a hang.
        """
        from ray_tpu.exceptions import ObjectLostError

        with self._lock:
            if self._stopped.is_set() or not self._waiting_deps.get(oid):
                return
        try:
            entry = self.gcs.call("object_locations_get",
                                  {"object_id": oid}, timeout=5)
        except Exception:  # noqa: BLE001 — directory unreachable: retry arm
            entry = {}
        if entry.get("known") and (entry.get("inline") is not None
                                   or entry.get("nodes")):
            # Advertised copies exist: re-pull (recovered, or the holder
            # is dying and the directory hasn't heard). This arm resets
            # the ladder, but it is bounded by the GCS death sweep —
            # once the health checker marks the holder DEAD its
            # locations are pruned and the next failed pull's ladder
            # advances past this check.
            self._start_pull(oid)
            return
        if attempt == 0:
            with self._lock:
                submitters = {
                    self._task_submitters.get(qt.spec.task_id.binary())
                    for qt in self._waiting_deps.get(oid, [])}
            for conn in submitters:
                if conn is not None and conn.alive:
                    try:
                        conn.push("task_dep_lost", {"object_id": oid})
                    except Exception:  # noqa: BLE001 — submitter gone
                        pass
        if attempt < self._LOST_DEP_RETRIES:
            t = threading.Timer(self._LOST_DEP_BACKOFF_S,
                                self._handle_lost_dep, args=(oid, attempt + 1))
            t.daemon = True
            t.start()
            return
        with self._lock:
            waiters = self._waiting_deps.pop(oid, [])
            for qt in waiters:
                try:
                    self._queue.remove(qt)
                except ValueError:
                    pass
        for qt in waiters:
            tkey = qt.spec.task_id.binary()
            with self._lock:
                submitter = self._task_submitters.pop(tkey, None)
            err = serialization.serialize_exception(
                ObjectLostError(oid), qt.spec.name)
            if submitter is not None and submitter.alive:
                try:
                    submitter.push("task_result",
                                   {"task_id": qt.spec.task_id,
                                    "results": [], "error": err})
                except Exception:  # noqa: BLE001 — submitter gone
                    pass

    def _pull_object_pipelined(self, oid: ObjectID, entry: Dict[str, Any]) -> bool:
        """Windowed, multi-source chunk fetch into a pre-created buffer.

        The reference moves objects as flow-controlled chunk streams with
        multiple chunks in flight (`object_manager.h:206`,
        `object_buffer_pool.h`); same here, plus location-aware striping:
        `object_transfer_window` chunk requests stay pipelined at all
        times, spread round-robin across EVERY advertised location (full
        and partial), and the location set refreshes as the pull runs so
        peers that finish their own pulls become sources mid-transfer.
        """
        chunk_bytes = max(1, GLOBAL_CONFIG.object_transfer_chunk_bytes)
        window = max(1, GLOBAL_CONFIG.object_transfer_window)
        my_hex = self.node_id.hex()
        peers = _PeerSet(max(1, GLOBAL_CONFIG.object_transfer_max_peers))
        self._add_entry_peers(peers, entry, my_hex)

        size = int(entry.get("size") or 0)
        first_data: Optional[memoryview] = None
        if size <= 0:
            # Directory entry without a size: learn it from chunk 0.
            # Busy senders are retried with backoff (consuming their
            # redirect hints) — a busy seed must delay discovery, not
            # fail the pull outright.
            probe_deadline = time.monotonic() + 5.0
            while size <= 0 and time.monotonic() < probe_deadline:
                progress = False
                for addr in peers.snapshot():
                    try:
                        meta, data, _ = self._fetch_chunk(addr, oid, 0,
                                                          chunk_bytes)
                    except Exception:  # noqa: BLE001
                        peers.drop(addr)
                        continue
                    st = meta.get("st")
                    if st == "ok":
                        size = int(meta["s"])
                        first_data = data
                        break
                    if st == "busy":
                        progress = True  # alive sender: worth retrying
                        for alt in meta.get("alt") or ():
                            peers.add(self._addr_for_node(alt))
                    elif meta.get("s"):
                        size = int(meta["s"])  # partial holder knows size
                        break
                if size <= 0:
                    if not progress and not self._refresh_pull_peers(
                            oid, peers, my_hex):
                        break
                    time.sleep(0.05)
            if size <= 0:
                return False
        if self.store.contains(oid):
            return True
        # Same-host fast path: a holder sharing this host already has the
        # sealed bytes in /dev/shm — attach its segment by final name
        # (atomic-rename seal => never torn) and memcpy shm->shm, no
        # socket hop at all. Falls through to the chunk pull on any miss.
        if self._try_same_host_attach(oid, entry, size):
            return True
        try:
            buf = self.store.create(oid, size)
        except ObjectStoreFullError as e:
            # Non-retryable for this node: remember it so get_or_pull can
            # surface a typed error instead of the client retrying forever.
            with self._lock:
                self._pull_errors[oid] = str(e)
            raise
        state = _ActivePull(buf, size, chunk_bytes)
        with self._lock:
            self._active_pulls[oid] = state
        ok = False
        plan: Dict[str, Any] = {}
        pull_span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            pull_span = _tracing.get_tracer().start_span(
                "object.pull",
                attrs={"object": oid.hex()[:16], "size": size,
                       "node": self.node_id.hex()[:12]})
        try:
            from ray_tpu._native import copy_at

            if first_data is not None:
                n = min(len(first_data), size)
                copy_at(buf, 0, first_data[:n])
                state.mark_done(0)
            # Advertise the in-progress copy: later pullers stripe their
            # reads across us for the chunks we already hold, turning an
            # N-node broadcast into a tree instead of N unicasts from the
            # seed (the directory returns us under `partial_nodes`).
            try:
                self.gcs.call_async(
                    "object_location_add",
                    {"object_id": oid, "node_id": self.node_id,
                     "size": size, "partial": True})
            except Exception:  # noqa: BLE001 — advisory
                pass
            nchunks = max(1, -(-size // chunk_bytes))
            work = [i for i in range(nchunks) if i not in state.done]
            # Random chunk order per puller (BitTorrent's rarest-first
            # rationale): concurrent pullers fetching 0..N in lockstep
            # would hold identical prefixes and have nothing to trade —
            # disjoint early chunk sets are what make the partial-holder
            # swarm actually drain load off the seed.
            random.shuffle(work)
            plan.update({
                "lock": threading.Lock(),
                "work": deque(work),
                "completed": len(state.done),
                "last_progress": time.monotonic(),
                "abort": None,
            })
            if pull_span is not _tracing.NOOP_SPAN:
                # Per-chunk annotations (bounded): chunk workers append
                # (idx, ms, source) samples under plan["lock"].
                plan["trace_chunks"] = []
            # Stall-based abort, not a fixed bandwidth floor: as long as
            # chunks keep landing the pull may take as long as it takes
            # (a healthy 10 MB/s WAN link must not be declared dead);
            # only rpc_call_timeout_s with zero progress aborts.
            stall_s = GLOBAL_CONFIG.rpc_call_timeout_s
            n_workers = min(window, max(1, len(plan["work"])))
            threads = [
                threading.Thread(
                    target=self._pull_chunk_worker,
                    args=(oid, state, peers, plan, stall_s),
                    name=f"pull-{oid.hex()[:8]}-{i}", daemon=True)
                for i in range(n_workers)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if plan["abort"] is not None:
                logger.warning("pull of %s aborted: %s", oid, plan["abort"])
            ok = plan["abort"] is None and len(state.done) >= nchunks
            if ok:
                self.store.seal(oid)
                with self._lock:
                    self._pull_errors.pop(oid, None)
                try:
                    self.gcs.call("object_location_add",
                                  {"object_id": oid, "node_id": self.node_id,
                                   "size": size}, timeout=10)
                except Exception:  # noqa: BLE001 — heartbeat re-announces
                    with self._lock:
                        self._unannounced_objects[oid] = size
            return ok
        finally:
            if pull_span is not _tracing.NOOP_SPAN:
                pull_span.set_attr("chunks", max(1, -(-size // chunk_bytes)))
                pull_span.set_attr("chunk_samples",
                                   plan.get("trace_chunks") or [])
            pull_span.end(error=None if ok else "pull failed or aborted")
            with self._lock:
                self._active_pulls.pop(oid, None)
            if not ok:
                try:
                    # Drop our export first so delete() can close+unlink the
                    # segment cleanly (workers have all joined by here).
                    buf.release()
                except Exception:  # noqa: BLE001
                    pass
                self.store.delete(oid)  # never leave an unsealed buffer
                # Prompt best-effort deregistration; the heartbeat loop
                # retries until a remove definitely landed.
                with self._lock:
                    self._stale_partials.add(oid)
                try:
                    self.gcs.call(
                        "object_location_remove",
                        {"object_id": oid, "node_id": self.node_id,
                         "partial": True}, timeout=5)
                    with self._lock:
                        self._stale_partials.discard(oid)
                except Exception:  # noqa: BLE001 — heartbeat retries
                    pass

    # ---------------------------------------------------- same-host attach

    def _session_suffix_for(self, node_hex: str) -> Optional[str]:
        """shm session suffix of a SAME-HOST holder; None when the node
        is remote, dead, or unknown. In-process registry first (free),
        then the directory's SessionSuffix (hostname-gated), then the
        peer RPC — cached, since a node's suffix never changes."""
        peer = _LOCAL_RAYLETS.get(node_hex)
        if peer is not None:
            return peer.session_suffix
        cached = self._peer_suffix_cache.get(node_hex)
        if cached is not None:
            return cached or None  # "" caches a known-remote node
        my_host = self._node_info.hostname if self._node_info else ""
        suffix = ""
        try:
            for n in self.gcs.call("get_nodes", timeout=5):
                if n["NodeID"] != node_hex or not n["Alive"]:
                    continue
                if n.get("NodeManagerHostname") != my_host:
                    break  # different host: shm can't reach it
                suffix = n.get("SessionSuffix") or ""
                break
        except Exception:  # noqa: BLE001 — advisory; chunk pull covers it
            return None
        if not suffix:
            try:
                addr = self._addr_for_node(node_hex)
                if addr:
                    resp = self._peer(addr).call("get_session_suffix",
                                                 timeout=5)
                    suffix = resp.get("session_suffix") or ""
            except Exception:  # noqa: BLE001
                suffix = ""
        # raylint: disable=RL011,RL012 — keyed by node id (bounded by lifetime cluster membership, ids never reused); a dead node's entry is inert: the directory stops listing it as a holder, so the key is never consulted again
        self._peer_suffix_cache[node_hex] = suffix
        return suffix or None

    def _try_same_host_attach(self, oid: ObjectID, entry: Dict[str, Any],
                              size: int) -> bool:
        """Adopt a sealed object from a same-host holder's shm segment
        into this node's store, bypassing the chunk protocol entirely.
        Only FULL holders qualify (a partial holder's segment is
        unsealed => unattachable by final name, by construction).
        Declines whenever a transfer-shaping hook is armed on either
        end, so benches that model a network keep measuring the
        network."""
        if not GLOBAL_CONFIG.object_transfer_same_host_attach:
            return False
        if self._chunk_fetch_delay_s:
            return False  # this puller models per-RPC RTT: stay honest
        my_hex = self.node_id.hex()
        for n in entry.get("nodes") or ():
            node_hex = n.hex() if hasattr(n, "hex") else str(n)
            if node_hex == my_hex:
                continue
            peer = _LOCAL_RAYLETS.get(node_hex)
            if peer is not None and (peer._chunk_serve_delay_s
                                     or peer._chunk_serve_bw_bps):
                continue  # holder models a link: pull through it instead
            suffix = self._session_suffix_for(node_hex)
            if not suffix:
                continue
            if self._attach_copy_from_segment(oid, suffix, size):
                return True
        return False

    def _attach_copy_from_segment(self, oid: ObjectID, peer_suffix: str,
                                  size: int) -> bool:
        """Adopt `rtpu_{peer_suffix}_{oid}` as this node's copy via a
        tmpfs HARDLINK to our own session name — zero bytes moved. Both
        names share the inode; the holder's eventual unlink drops only
        its name, so our copy's lifetime is independent (POSIX frees the
        pages when the last name AND mapping are gone). The final name
        only exists AFTER the holder's atomic-rename seal, so the link
        target is complete by construction. Pool-recycle safety: a
        holder's SegmentPool rewrites an inode only after the GCS
        confirmed the object freed cluster-wide — at which point reads
        of it anywhere are already undefined, same as holder-local
        zero-copy views. Falls back to a memcpy adoption where shm is
        not a linkable filesystem."""
        import os as _os

        from ray_tpu.core.object_store import (
            _SHM_DIR,
            _STAGING,
            _segment_name,
        )

        if not _STAGING:  # no linkable /dev/shm on this platform
            return self._attach_memcpy_from_segment(oid, peer_suffix, size)
        src = _os.path.join(_SHM_DIR, _segment_name(peer_suffix, oid))
        dst = _os.path.join(_SHM_DIR,
                            _segment_name(self.session_suffix, oid))
        try:
            _os.link(src, dst)
        except FileNotFoundError:
            return False  # evicted/spilled since the directory answered
        except FileExistsError:
            if self.store.contains(oid):
                return True  # raced another pull of the same object
            # Stale file under our name (ours to manage): replace it.
            try:
                _os.unlink(dst)
                _os.link(src, dst)
            except OSError:
                return False
        except OSError:
            return self._attach_memcpy_from_segment(oid, peer_suffix,
                                                    size)
        try:
            if _os.stat(dst).st_size < size:
                _os.unlink(dst)
                return False  # stale directory size: not a copy to trust
            try:
                self.store.adopt(oid, size)
            except ObjectStoreFullError as e:
                with self._lock:
                    self._pull_errors[oid] = str(e)
                _os.unlink(dst)
                raise
        except OSError:
            return False  # holder unlinked the inode mid-adopt: chunk path
        with self._lock:
            self._pull_errors.pop(oid, None)
            self._attach_hits += 1
            self._attach_bytes += size
        self._announce_attached(oid, size)
        return True

    def _attach_memcpy_from_segment(self, oid: ObjectID, peer_suffix: str,
                                    size: int) -> bool:
        """Portability fallback for `_attach_copy_from_segment`: attach
        the holder's segment read-only and memcpy it into our own store
        (create -> copy -> seal). An open mapping keeps the bytes alive
        for the copy even if the holder unlinks mid-read (POSIX)."""
        from multiprocessing import shared_memory

        from ray_tpu._native import copy_at
        from ray_tpu.core.object_store import _segment_name, _untrack

        try:
            shm = shared_memory.SharedMemory(
                name=_segment_name(peer_suffix, oid))
        except FileNotFoundError:
            return False  # evicted/spilled since the directory answered
        except Exception:  # noqa: BLE001 — permissions, platform quirks
            return False
        _untrack(shm)  # the holder owns the segment's lifetime, not us
        try:
            if shm.size < size:
                return False  # stale directory size: not our copy to trust
            try:
                buf = self.store.create(oid, size)
            except ObjectStoreFullError as e:
                with self._lock:
                    self._pull_errors[oid] = str(e)
                raise
            copy_at(buf, 0, shm.buf[:size])
            self.store.seal(oid)
            with self._lock:
                self._pull_errors.pop(oid, None)
                self._attach_hits += 1
                self._attach_bytes += size
            self._announce_attached(oid, size)
            return True
        finally:
            try:
                shm.close()
            except BufferError:
                pass  # transient view still alive; kernel reclaims at exit

    def _announce_attached(self, oid: ObjectID, size: int):
        """Register this node as a holder of a just-adopted object so
        later pullers can route (or attach) to us."""
        try:
            self.gcs.call("object_location_add",
                          {"object_id": oid, "node_id": self.node_id,
                           "size": size}, timeout=10)
        except Exception:  # noqa: BLE001 — heartbeat re-announces
            with self._lock:
                self._unannounced_objects[oid] = size

    def _pull_chunk_worker(self, oid: ObjectID, state: _ActivePull,
                           peers: _PeerSet, plan: Dict[str, Any],
                           stall_s: float):
        """One window slot: keeps exactly one chunk request in flight,
        drawing indices from the shared work queue until drained/abort.
        W slots over one peer connection = W pipelined requests (message
        ids multiplex), so per-chunk RTT no longer serializes the pull."""
        from ray_tpu._native import copy_at

        refetch_every = max(
            1, GLOBAL_CONFIG.object_transfer_refetch_location_chunks)
        my_hex = self.node_id.hex()
        while True:
            with plan["lock"]:
                if plan["abort"] is not None or not plan["work"]:
                    return
                idx = plan["work"].popleft()
            offset = idx * state.chunk_bytes
            length = min(state.chunk_bytes, state.size - offset)
            attempts = 0
            while True:
                with plan["lock"]:
                    stalled = (time.monotonic() - plan["last_progress"]
                               > stall_s)
                if stalled:
                    with plan["lock"]:
                        plan["abort"] = (
                            f"no progress for {stall_s:.0f}s "
                            f"(stuck on chunk {idx})")
                    return
                addr = peers.next()
                if addr is None:
                    if not self._refresh_pull_peers(oid, peers, my_hex):
                        # A FRESH directory answer with zero locations:
                        # the object is gone, not merely cooling down.
                        with plan["lock"]:
                            plan["abort"] = "no live locations remain"
                        return
                    if len(peers) == 0:
                        # Sources exist but are in drop-cooldown (or the
                        # directory is catching up): wait them out rather
                        # than failing a pull whose sole holder had one
                        # transient RPC error. The deadline bounds this.
                        time.sleep(0.1)
                    continue
                try:
                    meta, data, sunk = self._fetch_chunk(
                        addr, oid, offset, length,
                        sink=state.buf[offset: offset + length])
                except Exception:  # noqa: BLE001 — peer died mid-pull
                    peers.drop(addr)
                    self._refresh_pull_peers(oid, peers, my_hex)
                    continue
                st = meta.get("st")
                if st == "ok" and (sunk == length or len(data) == length):
                    if not sunk:
                        copy_at(state.buf, offset, data)
                    state.mark_done(idx)
                    with plan["lock"]:
                        plan["completed"] += 1
                        completed = plan["completed"]
                        now_mono = time.monotonic()
                        chunks = plan.get("trace_chunks")
                        if chunks is not None and len(chunks) < 32:
                            chunks.append(
                                [idx, round((now_mono
                                             - plan["last_progress"]) * 1e3,
                                            2), addr])
                        plan["last_progress"] = now_mono
                    if completed % refetch_every == 0:
                        # Pick up sources that appeared mid-pull.
                        self._refresh_pull_peers(oid, peers, my_hex)
                    break
                if st == "busy":
                    # Sender sheds us: try the hinted holders first.
                    for alt in meta.get("alt") or ():
                        peers.add(self._addr_for_node(alt))
                elif meta.get("gone"):
                    # Peer no longer has ANY copy (evicted/deleted).
                    peers.drop(addr)
                # else "missing": a partial source that simply lacks this
                # chunk yet — keep it for the chunks it does have.
                attempts += 1
                if attempts % max(1, len(peers) or 1) == 0:
                    self._refresh_pull_peers(oid, peers, my_hex)
                    time.sleep(0.02)  # every source busy/missing: back off

    def _fetch_chunk(self, addr: str, oid: ObjectID, offset: int,
                     length: int, sink: Optional[memoryview] = None,
                     ) -> Tuple[Dict[str, Any], memoryview, int]:
        """One chunk RPC. With `sink` (the chunk's slice of the store
        buffer) a matching reply is received DIRECTLY into it — zero-copy
        on the receive side; `sunk` reports the bytes landed there.
        Returns (meta, spilled chunk bytes if not sunk, sunk)."""
        if self._chunk_fetch_delay_s:
            # Test/bench hook modeling per-RPC propagation latency: window
            # slots sleep concurrently, so window>1 hides it exactly the
            # way pipelining hides real RTT.
            time.sleep(self._chunk_fetch_delay_s)
        peer = self._peer(addr)
        req = msgpack.packb({"o": oid.binary(), "f": offset, "l": length,
                             "p": self.node_id.hex()})
        if sink is not None:
            raw, sunk = peer.call_raw_into(
                "pull_object_chunk", req, sink,
                timeout=GLOBAL_CONFIG.rpc_call_timeout_s)
        else:
            raw = peer.call_raw("pull_object_chunk", req,
                                timeout=GLOBAL_CONFIG.rpc_call_timeout_s)
            sunk = 0
        meta, data = _unpack_chunk_reply(raw)
        return meta, data, sunk

    def _addr_for_node(self, node_hex: str,
                       nodes: Optional[List[Dict[str, Any]]] = None,
                       ) -> Optional[str]:
        """Raylet address of a node: gossiped view first, GCS fallback.
        `nodes` is an optional pre-fetched get_nodes() answer so batch
        resolution pays one directory round trip, not one per node."""
        addr = self._cluster_view.get(node_hex, {}).get("address")
        if addr:
            return addr
        if nodes is None:
            try:
                nodes = self.gcs.call("get_nodes")
            except Exception:  # noqa: BLE001 — resolution is best-effort
                return None
        return next((n["RayletAddress"] for n in nodes
                     if n["NodeID"] == node_hex and n["Alive"]), None)

    def _add_entry_peers(self, peers: _PeerSet, entry: Dict[str, Any],
                         my_hex: str) -> int:
        """Resolve a directory entry's locations (full + partial) to raylet
        addresses and add them as stripe sources. Returns the number of
        advertised non-self locations (whether or not each add succeeded —
        a cooling-down peer still counts as an advertised source)."""
        hexes: List[str] = []
        for n in list(entry.get("nodes") or ()) + \
                list(entry.get("partial_nodes") or ()):
            h = n.hex() if hasattr(n, "hex") else str(n)
            if h != my_hex:
                hexes.append(h)
        nodes_cache = None
        for h in hexes:
            addr = self._cluster_view.get(h, {}).get("address")
            if addr is None:
                if nodes_cache is None:
                    try:
                        nodes_cache = self.gcs.call("get_nodes")
                    except Exception:  # noqa: BLE001
                        nodes_cache = []
                addr = self._addr_for_node(h, nodes_cache)
            peers.add(addr)
        return len(hexes)

    def _refresh_pull_peers(self, oid: ObjectID, peers: _PeerSet,
                            my_hex: str) -> bool:
        """Re-query the directory for locations that appeared since the
        pull started (rate-limited across this pull's workers). Returns
        False only when a FRESH directory answer advertises no location at
        all — a peer in drop-cooldown or a failed/rate-limited query is
        'undecided' (True), and the pull deadline bounds how long workers
        keep waiting on undecided sources."""
        if not peers.may_refresh():
            return True  # rate-limited: undecided
        try:
            entry = self.gcs.call("object_locations_get",
                                  {"object_id": oid}, timeout=5)
        except Exception:  # noqa: BLE001 — GCS unreachable: undecided
            return True
        advertised = self._add_entry_peers(peers, entry, my_hex)
        return len(peers) > 0 or advertised > 0

    def _peer(self, address: str) -> RpcClient:
        stale = []
        with self._lock:
            client = self._peer_clients.get(address)
            if client is None or client.is_closed:
                # Amortized pruning on the (rare) dial path: node churn
                # must not grow the peer cache by one client — reconnect
                # state and all — per address that ever existed. A peer
                # is stale once closed or once no live node advertises
                # its address anymore (closed outside the lock).
                live = {e.get("address")
                        for e in self._cluster_view.values()}
                for addr in list(self._peer_clients):
                    c = self._peer_clients[addr]
                    if c.is_closed or (live and addr not in live):
                        stale.append(self._peer_clients.pop(addr))
                client = RpcClient(address, name=f"raylet-peer")
                self._peer_clients[address] = client
        for c in stale:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — already closed/dead peer
                pass
        return client

    # A puller with no chunk served for this long no longer counts against
    # the sender-side concurrency gate (its transfer finished or died).
    _OUTBOUND_ACTIVE_S = 2.0
    # Redirect hints expire: a node that pulled the object from us may
    # have evicted it since, and shedding pullers to a non-holder wedges
    # them between a busy seed and a dead-end hint.
    _HINT_TTL_S = 30.0
    # Coverage-ledger entries idle this long belong to dead/finished
    # transfers — pruned so they can't exempt a restarted puller from
    # the gate or pin the ledger at its size cap.
    _COVERAGE_TTL_S = 60.0

    def _admit_puller(self, oid: ObjectID,
                      puller: Optional[str]) -> Optional[List[str]]:
        """Sender-side fairness: None admits the request; a list of
        redirect hints (node hexes that already pulled the full object
        from us) means 'busy'. A puller mid-transfer is always admitted,
        the gate is per object, and a new puller is only shed when there
        IS an alternative holder to hint at — shedding with nowhere to go
        would fail pulls of an object whose sole copy lives here. So N
        simultaneous pullers self-organize into a tree instead of
        convoying on one NIC, and a lone source still serves everyone."""
        limit = GLOBAL_CONFIG.object_transfer_sender_concurrency
        if not limit or not puller:
            return None
        oid_b = oid.binary()
        now = time.monotonic()
        with self._outbound_lock:
            for k, ts in list(self._outbound_last_seen.items()):
                if now - ts > self._OUTBOUND_ACTIVE_S:
                    del self._outbound_last_seen[k]
            for k, rec in list(self._outbound_chunks.items()):
                if now - rec[1] > self._COVERAGE_TTL_S:
                    del self._outbound_chunks[k]
            key = (oid_b, puller)
            active = sum(1 for (o, p) in self._outbound_last_seen
                         if o == oid_b and p != puller)
            alts = self._fresh_hints_locked(oid_b, puller, now)
            # _outbound_chunks membership exempts SLOW mid-transfer
            # pullers whose per-chunk cadence exceeds the activity
            # window — "mid-transfer is always admitted" must hold on a
            # trickling WAN link too, not just on fast LANs.
            if (key in self._outbound_last_seen
                    or key in self._outbound_chunks
                    or active < limit or not alts):
                self._outbound_last_seen[key] = now
                return None
            return alts

    def _fresh_hints_locked(self, oid_b: bytes, puller: str,
                            now: float) -> List[str]:
        """Non-expired redirect hints for an object (caller holds
        _outbound_lock); expired holders are pruned in place."""
        holders = self._completed_pullers.get(oid_b)
        if not holders:
            return []
        for h, ts in list(holders.items()):
            if now - ts > self._HINT_TTL_S:
                del holders[h]
        if not holders:
            self._completed_pullers.pop(oid_b, None)
            return []
        return [h for h in holders if h != puller]

    def _record_outbound(self, oid: ObjectID, puller: Optional[str],
                         offset: int, nbytes: int, size: int):
        """Per-puller coverage bookkeeping feeding the fairness gate's
        redirect hints. With the gate disabled nothing ever reads these
        tables — and nothing prunes them — so record nothing."""
        if not puller or not GLOBAL_CONFIG.object_transfer_sender_concurrency:
            return
        key = (oid.binary(), puller)
        now = time.monotonic()
        with self._outbound_lock:
            self._outbound_last_seen[key] = now
            if len(self._outbound_chunks) >= 1024 and \
                    key not in self._outbound_chunks:
                # Evict the LEAST-RECENTLY-ACTIVE entry, not the oldest
                # insertion — a live trickling puller must keep its
                # coverage record under sustained many-object load.
                self._outbound_chunks.pop(min(
                    self._outbound_chunks,
                    key=lambda k: self._outbound_chunks[k][1]))
            rec = self._outbound_chunks.setdefault(key, [{}, now])
            offsets = rec[0]
            rec[1] = now
            offsets[offset] = max(offsets.get(offset, 0), nbytes)
            # Distinct-coverage completion: a re-served chunk counts once,
            # so retries can't mark a partial puller as a full holder.
            if sum(offsets.values()) >= size:
                self._outbound_chunks.pop(key, None)
                if len(self._completed_pullers) >= 256:
                    self._completed_pullers.pop(
                        next(iter(self._completed_pullers)))
                holders = self._completed_pullers.setdefault(
                    oid.binary(), {})
                if len(holders) < 16 or puller in holders:
                    holders[puller] = time.monotonic()

    def _serve_chunk_raw(self, conn: Connection, payload: bytes):
        """Raw-RPC chunk server (`pull_object_chunk`): serves a slice of a
        sealed object — or of an in-progress pull whose covering chunks
        already landed — as a memoryview of the store segment. The reply
        is sent inside the handler (DEFERRED) so the segment stays pinned
        for exactly the duration of the vectored zero-copy write."""
        req = msgpack.unpackb(payload)
        oid = ObjectID(req["o"])
        offset = int(req["f"])
        length = int(req["l"])
        puller = req.get("p")
        if self._chunk_serve_delay_s:
            time.sleep(self._chunk_serve_delay_s)  # test/bench RTT hook
        alts = self._admit_puller(oid, puller)
        if alts is not None:
            return _pack_chunk_reply({"st": "busy", "alt": alts})
        msg_id = conn.current_msg_id
        self.store.pin(oid)
        try:
            buf = self.store.get_buffer(oid)
            size = len(buf) if buf is not None else 0
            if buf is None:
                state = self._active_pulls.get(oid)
                if state is not None and state.covers(offset, length):
                    buf, size = state.buf, state.size
                elif state is not None:
                    return _pack_chunk_reply({"st": "missing", "s": state.size})
                else:
                    # Re-check the store: our own pull may have sealed (and
                    # popped _active_pulls) between the two lookups — a
                    # spurious `gone` would permanently blacklist us in
                    # the requester's peer set.
                    buf = self.store.get_buffer(oid)
                    if buf is None:
                        return _pack_chunk_reply({"st": "missing",
                                                  "gone": True})
                    size = len(buf)
            if offset >= size:
                return _pack_chunk_reply({"st": "missing", "s": size})
            end = min(offset + length, size) if length else size
            if self._chunk_serve_bw_bps:
                # Serialized per-node egress: concurrent transfers share
                # the one modeled link instead of sleeping in parallel.
                with self._link_lock:
                    # Sleeping under the lock IS the model: concurrent
                    # sends must serialize on the one emulated link.
                    time.sleep(  # raylint: disable=RL002
                        (end - offset) / self._chunk_serve_bw_bps)
            self._record_outbound(oid, puller, offset, end - offset, size)
            with self._outbound_lock:
                # Cross-node byte meter: benches A/B locality routing by
                # summing this over all raylets (attach hits never pass
                # here — that's the point).
                self._chunk_bytes_served += end - offset
            conn.reply_raw(msg_id, "pull_object_chunk",
                           _pack_chunk_reply({"st": "ok", "s": size},
                                             buf[offset:end]))
            return DEFERRED
        finally:
            self.store.unpin(oid)

    # raylint: disable=RL014 — kept for debug tooling / mixed-version peers
    def handle_pull_object(self, conn: Connection, data: Dict[str, Any]):
        """Legacy pickled transfer surface: one chunk (or, without offset,
        the whole object). The pipelined puller speaks the raw
        `pull_object_chunk` method instead; this stays for debug tooling
        and mixed-version peers."""
        oid: ObjectID = data["object_id"]
        buf = self.store.get_buffer(oid)
        if buf is None:
            return {"data": None}
        if "offset" not in data:
            return {"data": bytes(buf), "size": len(buf)}
        off = int(data["offset"])
        length = int(data.get("length") or len(buf))
        return {"data": bytes(buf[off: off + length]), "size": len(buf)}

    def handle_get_or_pull(self, conn: Connection, data: Dict[str, Any]):
        """Local client wants this object available in the node store.

        Event-driven (no server-side poll loop — a blocking handler would
        also head-of-line-block every other RPC on the caller's
        connection): answers immediately with local/inline, or registers
        the connection as a waiter, starts a pull, and later pushes
        `object_ready` / `object_unavailable` down the caller's channel.
        """
        oid: ObjectID = data["object_id"]
        # get_buffer (not contains) so spilled objects are restored to shm
        # before we tell the client to attach the segment.
        if self.store.get_buffer(oid) is not None:
            return {"status": "local"}
        entry = self.gcs.call("object_locations_get", {"object_id": oid}, timeout=10)
        if entry.get("known") and entry.get("inline") is not None:
            return {"status": "inline", "data": entry["inline"]}
        with self._lock:
            pull_error = self._pull_errors.get(oid)
            if pull_error is not None:
                return {"status": "error", "error": pull_error}
            waiters = self._object_waiters[oid]
            if conn not in waiters:
                waiters.append(conn)
        self._start_pull(oid)
        # Re-check after registration: the pull may have completed between
        # the first check and the waiter insert (notify already fired).
        if self.store.get_buffer(oid) is not None:
            with self._lock:
                ws = self._object_waiters.get(oid)
                if ws is not None:
                    try:
                        ws.remove(conn)
                    except ValueError:
                        pass
                    if not ws:
                        self._object_waiters.pop(oid, None)
            return {"status": "local"}
        # has_copies tells the owner whether reconstruction is needed: the
        # entry exists but every holding node is gone.
        return {"status": "pending", "known": bool(entry.get("known")),
                "has_copies": bool(entry.get("nodes"))}

    def _notify_object_waiters(self, oid: ObjectID, method: str):
        with self._lock:
            conns = self._object_waiters.pop(oid, [])
        for conn in conns:
            if conn.alive:
                try:
                    conn.push(method, {"object_id": oid})
                except Exception:  # noqa: BLE001 — client gone
                    pass

    def _on_object_local(self, oid: ObjectID):
        """Dependency became available locally (or inline): unblock tasks."""
        with self._lock:
            waiters = self._waiting_deps.pop(oid, [])
            for qt in waiters:
                qt.deps_remaining.discard(oid)
        if waiters:
            self._dispatch_event.set()
        self._notify_object_waiters(oid, "object_ready")

    def handle_cancel_task(self, conn: Connection, data: Dict[str, Any]):
        """Cancel a queued or running normal task (reference
        `ray.cancel`): queued tasks are dropped; running tasks get an
        interrupt signal (or, with force, their worker is killed). The
        submitter receives TaskCancelledError either way, and cancelled
        tasks are never retried."""
        import signal as _signal

        from ray_tpu.exceptions import TaskCancelledError

        task_id = data["task_id"]
        force = bool(data.get("force"))
        tkey = task_id.binary()
        err = serialization.serialize_exception(
            TaskCancelledError(task_id), "cancelled")
        with self._lock:
            queued = next((qt for qt in self._queue
                           if qt.spec.task_id.binary() == tkey), None)
            if queued is not None:
                self._queue.remove(queued)
                for dep in queued.deps_remaining:
                    waiters = self._waiting_deps.get(dep)
                    if waiters and queued in waiters:
                        waiters.remove(queued)
                submitter = self._task_submitters.pop(tkey, None)
        if queued is not None:
            if submitter is not None and submitter.alive:
                try:
                    submitter.push("task_result",
                                   {"task_id": task_id, "results": [],
                                    "error": err})
                except Exception:  # noqa: BLE001
                    pass
            return {"cancelled": "queued"}
        with self._lock:
            entry = self._running.get(tkey)
        if entry is None:
            return {"cancelled": None}  # already finished (or elsewhere)
        spec, worker = entry
        if not force:
            # Cooperative interrupt: tell the worker WHICH task to cancel —
            # it signals itself after recording the id, and its handler
            # verifies the id before raising, so a cancel can never hit a
            # different task the worker has since started. Normal
            # task_done reports the error (crashed=False -> no retry).
            try:
                worker.conn.push("cancel_exec", {"task_id": task_id})
                return {"cancelled": "interrupted"}
            except Exception:  # noqa: BLE001 — worker gone
                return {"cancelled": None}
        # Force: pre-empt the result so the submitter sees cancellation
        # (not WorkerCrashedError), then kill the worker process.
        with self._lock:
            self._running.pop(tkey, None)
            submitter = self._task_submitters.pop(tkey, None)
        if submitter is not None and submitter.alive:
            try:
                submitter.push("task_result",
                               {"task_id": task_id, "results": [],
                                "error": err})
            except Exception:  # noqa: BLE001
                pass
        if worker.proc is not None and worker.proc.poll() is None:
            try:
                worker.proc.terminate()
            except Exception:  # noqa: BLE001
                pass
        return {"cancelled": "killed"}

    def handle_cancel_object_wait(self, conn: Connection, data: Dict[str, Any]):
        """Client gave up on a get (timeout): drop its waiter entry so the
        raylet stops pulling on behalf of nobody."""
        oid: ObjectID = data["object_id"]
        with self._lock:
            ws = self._object_waiters.get(oid)
            if ws is not None:
                try:
                    ws.remove(conn)
                except ValueError:
                    pass
                if not ws:
                    del self._object_waiters[oid]
        return {}

    def handle_delete_objects(self, conn: Connection, data: Dict[str, Any]):
        skip = {o.binary() for o in data.get("skip_unlink", ())}
        for oid in data["object_ids"]:
            self.store.delete(oid, skip_unlink=oid.binary() in skip)
        return {}

    def handle_set_resource(self, conn: Connection, data: Dict[str, Any]):
        """Dynamic custom resources (reference
        `experimental/dynamic_resources.py` -> raylet SetResource): set a
        resource's TOTAL capacity on this node at runtime; queued tasks
        waiting on it re-dispatch."""
        name = data["resource_name"]
        capacity = float(data["capacity"])
        if name in ("CPU", "TPU", "memory", "object_store_memory") \
                or name.startswith("node:"):
            raise ValueError(
                f"cannot dynamically override built-in resource {name!r}")
        if capacity < 0 or not math.isfinite(capacity):
            # NaN would poison the ledger permanently: the abs()<eps
            # delete guard and every feasibility comparison are False
            # against NaN.
            raise ValueError(
                f"resource capacity must be finite and >= 0, "
                f"got {capacity}")
        self.resources.set_total(name, capacity)
        self._dispatch_event.set()
        return {"total": capacity}

    # ------------------------------------------------- placement group 2PC

    def handle_prepare_bundle(self, conn: Connection, data: Dict[str, Any]):
        pg = data["pg"]
        idx: int = data["bundle_index"]
        bundle: Dict[str, float] = pg.bundles[idx]
        if not self.resources.try_acquire(bundle):
            return {"ok": False}
        with self._lock:
            self._bundles[(pg.pg_id.binary(), idx)] = {
                "pg": pg, "bundle": bundle, "state": "prepared"}
        return {"ok": True}

    def handle_commit_bundle(self, conn: Connection, data: Dict[str, Any]):
        pg_id: PlacementGroupID = data["pg_id"]
        idx: int = data["bundle_index"]
        with self._lock:
            rec = self._bundles.get((pg_id.binary(), idx))
            if rec is None or rec["state"] != "prepared":
                return {"ok": False}
            rec["state"] = "committed"
        pg = rec["pg"]
        formatted: Dict[str, float] = {}
        for base, amt in rec["bundle"].items():
            formatted[pg.bundle_resource_name(base, idx)] = amt
            wc = pg.wildcard_resource_name(base)
            formatted[wc] = formatted.get(wc, 0) + amt
        rec["formatted"] = formatted
        self.resources.add_resources(formatted)
        return {"ok": True}

    def handle_cancel_bundle(self, conn: Connection, data: Dict[str, Any]):
        pg_id: PlacementGroupID = data["pg_id"]
        idx: int = data["bundle_index"]
        with self._lock:
            rec = self._bundles.pop((pg_id.binary(), idx), None)
        if rec is not None and rec["state"] == "prepared":
            self.resources.release(rec["bundle"])
        return {}

    def handle_return_bundle(self, conn: Connection, data: Dict[str, Any]):
        pg_id: PlacementGroupID = data["pg_id"]
        idx: int = data["bundle_index"]
        with self._lock:
            rec = self._bundles.pop((pg_id.binary(), idx), None)
        if rec is None:
            return {}
        if rec["state"] == "committed":
            self.resources.remove_resources(rec.get("formatted", {}))
            self.resources.release(rec["bundle"])
        elif rec["state"] == "prepared":
            self.resources.release(rec["bundle"])
        return {}

    # --------------------------------------------------------------- debug

    def handle_get_session_suffix(self, conn: Connection, data=None):
        return {"session_suffix": self.session_suffix,
                "session_dir": self.session_dir}

    def handle_debug_state(self, conn: Connection, data=None):
        total, avail = self.resources.snapshot()
        with self._lock:
            return {
                "node_id": self.node_id.hex(),
                "queued": len(self._queue),
                "running": len(self._running),
                "workers": self.pool.num_alive(),
                "worker_spawns": dict(self.pool.spawn_counts),
                "forge_alive": bool(self.forge is not None
                                    and self.forge.alive),
                "resources_total": total,
                "resources_available": avail,
                "store": self.store.stats(),
                "transfer": {
                    "attach_hits": self._attach_hits,
                    "attach_bytes": self._attach_bytes,
                    "chunk_bytes_served": self._chunk_bytes_served,
                },
            }
