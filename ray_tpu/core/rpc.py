"""Internal RPC: length-prefixed messages over TCP.

Plays the role of the reference's gRPC layer (`src/ray/rpc/grpc_server.h:73`,
`client_call.h:181`) for control-plane traffic between driver, GCS, raylets and
workers. Wire format per message:

    [4B LE length][msgpack envelope {i, k, m, e} ][payload bytes]

where `k` is req|resp|push, `m` the method name, `e` an error string on failed
responses. Payloads are cloudpickle for control messages; bulk object data is
raw bytes. Servers are thread-per-connection (connection counts here are tens,
not thousands); clients have a background reader so servers can push
unsolicited messages (task dispatch, pubsub) down the same connection.
"""

from __future__ import annotations

import itertools
import logging
import queue
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu.core import serialization
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.exceptions import RaySystemError
from ray_tpu.observability import tracing as _tracing

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<I")


class ConnectionLost(RaySystemError):
    pass


# --- Chaos fault hook (ray_tpu/chaos) ----------------------------------------
# Installed by the chaos plane's RpcFaultInjector; None in production. The
# disabled path costs exactly one module-global None check on the send path
# (proven inert by bench_chaos's A-B-A overhead measurement). When installed,
# the filter sees (client_name, address, method) BEFORE a request frame is
# sent and returns an action:
#   None / "pass"        send normally
#   ("delay", seconds)   sleep, then send — a slow link
#   "error"              raise ConnectionLost without sending — a reset
#                        connection (ReconnectingClient re-dials, the actor
#                        submit path retries)
#   "drop"               swallow the send — a blackhole partition. Blocking
#                        callers run into their own RPC timeout; pipelined
#                        callers with a callback get the loss envelope (a
#                        drop on an ordered stream is indistinguishable from
#                        a dead connection to the sender).
# Only REQUEST frames from RpcClient are filtered: every cross-process hop in
# the system originates at some client, so node-pair partitions are expressed
# by matching the client's name/address, and response/push frames of an
# unfiltered peer stay intact (a real partition would cut both directions —
# injectors install matching filters on both sides when they want that).

_CHAOS_FILTER = None


def install_chaos_filter(fn) -> None:
    """Install `fn(client_name, address, method) -> action` as the
    process-wide RPC fault filter (see the action table above)."""
    global _CHAOS_FILTER
    _CHAOS_FILTER = fn


def clear_chaos_filter() -> None:
    global _CHAOS_FILTER
    _CHAOS_FILTER = None


def _chaos_action(client: "RpcClient", method: str):
    """Evaluate the installed filter defensively: a broken filter must
    degrade to fault-free RPC, never take the control plane down."""
    try:
        return _CHAOS_FILTER(client._name, client.address, method)
    except Exception:  # noqa: BLE001 — chaos tooling must not add faults
        logger.exception("chaos filter raised; treating as pass")
        return None


def _as_view(p) -> memoryview:
    v = p if isinstance(p, memoryview) else memoryview(p)
    if v.format != "B" or v.ndim != 1:
        v = v.cast("B") if v.contiguous else memoryview(bytes(v))
    return v


def _sendall_vectored(sock: socket.socket, views: list):
    """sendall over a list of buffers without concatenating them (one
    gather syscall per iteration; partial sends trim the head view)."""
    views = [v for v in views if v.nbytes]
    while views:
        sent = sock.sendmsg(views)
        while sent:
            if sent >= views[0].nbytes:
                sent -= views[0].nbytes
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


# Below this, one sendall of a joined frame beats the sendmsg setup cost.
_VECTOR_MIN_BYTES = 64 * 1024


def _send_msg(sock: socket.socket, envelope: dict, payload, lock: threading.Lock):
    """Frame and send one message. `payload` is bytes, a memoryview, or a
    list of buffer parts — large parts are sent with a vectored gather
    write, so chunk payloads (memoryview slices of sealed store segments)
    reach the socket without an intermediate copy."""
    env = msgpack.packb(envelope)
    parts = payload if isinstance(payload, (list, tuple)) else (payload,)
    views = [_as_view(p) for p in parts]
    plen = sum(v.nbytes for v in views)
    hdr = _HDR.pack(len(env) + 4 + plen) + _HDR.pack(len(env)) + env
    with lock:
        if plen < _VECTOR_MIN_BYTES:
            sock.sendall(hdr + b"".join(views))
        else:
            _sendall_vectored(sock, [memoryview(hdr), *views])


def _recv_into_exact(sock: socket.socket, view: memoryview):
    """Fill `view` completely from the socket (single-copy receive)."""
    pos = 0
    n = view.nbytes
    while pos < n:
        r = sock.recv_into(view[pos:])
        if r == 0:
            raise ConnectionLost("peer closed connection")
        pos += r


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    _recv_into_exact(sock, memoryview(buf))
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    (total,) = _HDR.unpack(_recv_exact(sock, 4))
    body = memoryview(bytearray(total))
    _recv_into_exact(sock, body)
    (elen,) = _HDR.unpack(body[:4])
    envelope = msgpack.unpackb(body[4 : 4 + elen])
    return envelope, bytes(body[4 + elen :])


# Handler return sentinel: the response will be sent later by the handler
# itself via Connection.reply(msg_id, ...) — used by long-running calls
# (e.g. actor_call_light) so the connection thread isn't parked while the
# method executes.
DEFERRED = object()


class Connection:
    """Server-side handle for one client connection; supports pushes."""

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self.send_lock = threading.Lock()
        self.meta: Dict[str, Any] = {}  # handlers stash identity here (node id, worker id)
        self.alive = True
        # msg id of the request currently being handled (connection threads
        # process requests serially; a DEFERRED handler must read this
        # synchronously in its body).
        self.current_msg_id = 0

    def reply(self, msg_id: int, method: str, data: Any = None,
              error: Optional[str] = None):
        """Send the response for a DEFERRED request."""
        env = {"i": msg_id, "k": "resp", "m": method}
        if error is not None:
            env["e"] = error
            payload = b""
        else:
            payload = serialization.dumps_ctrl(data)
        try:
            _send_msg(self.sock, env, payload, self.send_lock)
        except OSError as e:
            self.alive = False
            raise ConnectionLost(str(e))

    def reply_raw(self, msg_id: int, method: str, payload):
        """Send a raw-bytes response for a DEFERRED raw request. `payload`
        may be a list of buffer parts (vectored, zero-copy) — used by the
        object transfer plane so a handler can hold a pin on the store
        segment for exactly the duration of the send."""
        try:
            _send_msg(self.sock, {"i": msg_id, "k": "resp", "m": method},
                      payload, self.send_lock)
        except OSError as e:
            self.alive = False
            raise ConnectionLost(str(e))

    def push(self, method: str, data: Any):
        payload = serialization.dumps_ctrl(data)
        try:
            _send_msg(self.sock, {"i": 0, "k": "push", "m": method}, payload, self.send_lock)
        except OSError as e:
            self.alive = False
            raise ConnectionLost(str(e))

    def push_raw(self, method: str, payload: bytes):
        """Push a PRE-SERIALIZED payload: fan-out paths (pubsub delta
        batches) serialize one frame once and send it to N subscribers,
        instead of paying N pickles of identical content."""
        try:
            _send_msg(self.sock, {"i": 0, "k": "push", "m": method},
                      payload, self.send_lock)
        except OSError as e:
            self.alive = False
            raise ConnectionLost(str(e))

    def close(self):
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class RpcServer:
    """Thread-per-connection RPC server.

    Handlers: fn(conn: Connection, data: Any) -> Any. Raising propagates the
    error string to the caller, which re-raises RaySystemError.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, name: str = "rpc",
                 reuse_port: bool = False):
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # Opt-in REUSEPORT lets a restarted server (GCS failover) rebind its
        # old port while the previous incarnation's accepted sockets are
        # still draining through FIN_WAIT/TIME_WAIT. Off by default so an
        # accidental double-bind stays a loud EADDRINUSE.
        if reuse_port and hasattr(socket, "SO_REUSEPORT"):
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        self._listener.bind((host, port))
        self._listener.listen(512)
        self.host, self.port = self._listener.getsockname()
        self.address = f"{self.host}:{self.port}"
        self._name = name
        self._handlers: Dict[str, Callable[[Connection, Any], Any]] = {}
        self._raw_handlers: Dict[str, Callable[[Connection, bytes], bytes]] = {}
        self._conns: Dict[int, Connection] = {}
        self._conn_counter = itertools.count()
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self.on_disconnect: Optional[Callable[[Connection], None]] = None

    def register(self, method: str, handler: Callable[[Connection, Any], Any]):
        # Keyed by method name, registered once at server bring-up.
        # raylint: disable=RL011 — the key space is fixed by the code
        self._handlers[method] = handler

    def register_raw(self, method: str,
                     handler: Callable[[Connection, bytes], bytes]):
        """Register a handler that speaks raw payload bytes (no pickle on
        either side). This is the cross-language surface: non-Python
        clients (cpp/) frame msgpack envelopes like everyone else but
        cannot produce or parse pickled payloads, so raw methods let them
        carry msgpack (or any agreed encoding) end to end."""
        # raylint: disable=RL011 — method names, registered at bring-up
        self._raw_handlers[method] = handler

    def register_instance(self, obj: Any, prefix: str = ""):
        """Register all `handle_*` methods of obj as RPC methods."""
        for attr in dir(obj):
            if attr.startswith("handle_"):
                self.register(prefix + attr[len("handle_") :], getattr(obj, attr))

    def start(self):
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self._name}-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            if self._stopped.is_set():
                # Stopped while blocked in accept: this connection belongs
                # to our successor (same port via REUSEPORT), not to us.
                try:
                    sock.close()
                except OSError:
                    pass
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = Connection(sock, f"{addr[0]}:{addr[1]}")
            cid = next(self._conn_counter)
            with self._lock:
                self._conns[cid] = conn
            t = threading.Thread(
                target=self._serve_conn, args=(cid, conn), name=f"{self._name}-conn{cid}", daemon=True
            )
            t.start()

    def _serve_conn(self, cid: int, conn: Connection):
        close_reason = "server stopping"
        try:
            while not self._stopped.is_set():
                envelope, payload = _recv_msg(conn.sock)
                if envelope["k"] != "req":
                    continue
                method = envelope["m"]
                handler = self._handlers.get(method)
                resp_env = {"i": envelope["i"], "k": "resp", "m": method}
                # Restore the caller's trace context for the handler (the
                # server half of wire propagation); reset after — this
                # connection thread serves many unrelated requests.
                trace_tok = None
                wire_t = envelope.get("t")
                if wire_t is not None:
                    trace_tok = _tracing.activate_wire(wire_t)
                try:
                    raw = self._raw_handlers.get(method)
                    if raw is not None:
                        conn.current_msg_id = envelope["i"]
                        out = raw(conn, payload)
                        if out is DEFERRED:
                            continue  # handler replied via conn.reply_raw()
                        _send_msg(conn.sock, resp_env, out, conn.send_lock)
                        continue
                    if handler is None:
                        raise RaySystemError(f"{self._name}: no handler for '{method}'")
                    data = serialization.loads(payload) if payload else None
                    conn.current_msg_id = envelope["i"]
                    result = handler(conn, data)
                    if result is DEFERRED:
                        continue  # handler replies via conn.reply()
                    out = serialization.dumps_ctrl(result)
                except Exception as e:
                    # Handler failures — including ConnectionLost from the
                    # handler's own outbound RPCs — must not tear down THIS
                    # connection; only IO errors on conn.sock do.
                    logger.debug("%s handler %s failed: %s", self._name, method,
                                 e, exc_info=True)
                    resp_env["e"] = f"{type(e).__name__}: {e}"
                    out = b""
                finally:
                    if trace_tok is not None:
                        _tracing.deactivate(trace_tok)
                _send_msg(conn.sock, resp_env, out, conn.send_lock)
        except (ConnectionLost, OSError) as e:
            close_reason = f"{type(e).__name__}: {e}"
        finally:
            if not self._stopped.is_set():
                logger.info("%s: connection from %s closed (%s)", self._name,
                            conn.peer, close_reason)
            conn.alive = False
            with self._lock:
                self._conns.pop(cid, None)
            if self.on_disconnect:
                try:
                    self.on_disconnect(conn)
                except Exception:
                    logger.exception("on_disconnect callback failed")
            conn.close()

    def stop(self):
        self._stopped.set()
        # shutdown() (not just close) wakes a thread blocked in accept();
        # a closed-but-still-blocked listener would otherwise keep its
        # kernel socket in LISTEN state and steal connections from a
        # restarted server on the same port.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            c.close()


class RpcClient:
    """Blocking RPC client with a background reader for responses + pushes."""

    def __init__(
        self,
        address: str,
        name: str = "client",
        push_handler: Optional[Callable[[str, Any], None]] = None,
        connect_timeout: Optional[float] = None,
        on_close: Optional[Callable[[], None]] = None,
    ):
        self.on_close = on_close
        host, port = address.rsplit(":", 1)
        timeout = connect_timeout or GLOBAL_CONFIG.rpc_connect_timeout_s
        deadline = time.monotonic() + timeout
        last_err: Optional[Exception] = None
        backoff = 0.05
        while True:
            try:
                self._sock = socket.create_connection((host, int(port)), timeout=5)
                break
            except OSError as e:
                last_err = e
                if time.monotonic() > deadline:
                    raise ConnectionLost(f"connect to {address} failed: {e}")
                # Exponential backoff, capped: a long outage (GCS restart)
                # must not spin the dial loop at 20 attempts/s for its
                # whole duration.
                time.sleep(min(backoff,
                               max(0.0, deadline - time.monotonic())))
                backoff = min(backoff * 2, 1.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        self.address = address
        self._name = name
        self._send_lock = threading.Lock()
        self._msg_counter = itertools.count(1)
        self._pending: Dict[int, dict] = {}
        self._pending_lock = threading.Lock()
        self._push_handler = push_handler
        self._closed = threading.Event()
        # Pushes dispatch from their own thread, NEVER the reader: a push
        # handler that blocks on a lock held by code awaiting an RPC
        # response over this client would otherwise deadlock the response
        # dispatch (observed: raylet _on_gcs_push vs _enqueue's gcs.call).
        self._push_queue: "queue.Queue" = queue.Queue()
        self._reader = threading.Thread(target=self._read_loop, name=f"{name}-reader", daemon=True)
        self._reader.start()
        if push_handler is not None:
            self._push_thread = threading.Thread(
                target=self._push_loop, name=f"{name}-push", daemon=True)
            self._push_thread.start()

    @property
    def is_closed(self) -> bool:
        return self._closed.is_set()

    def _push_loop(self):
        while not self._closed.is_set():
            try:
                item = self._push_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            method, payload = item
            try:
                data = serialization.loads(payload) if payload else None
                self._push_handler(method, data)
            except Exception:
                logger.exception("%s push handler failed", self._name)

    # Frames at or below this read as one recv (the control-plane common
    # case); larger frames parse the envelope first so chunk payloads can
    # stream straight into a registered sink buffer.
    _INLINE_FRAME_MAX = 64 * 1024

    def _peek_slot(self, envelope: dict) -> Optional[dict]:
        if envelope["k"] != "resp":
            return None
        with self._pending_lock:
            return self._pending.get(envelope["i"])

    def _read_one(self) -> Tuple[dict, bytes]:
        """Read one message — large-frame payloads land directly in a
        response's registered sink buffer when one matches (the zero-copy
        receive half of the transfer plane: chunk bytes go into the
        pre-created store segment with no intermediate buffers).

        The pending slot is only PEEKED here, never popped: if the
        connection dies mid-payload, the caller's slot must still be in
        _pending so the reader's drain delivers ConnectionLost (a popped
        slot would strand the caller until TimeoutError, skipping
        ReconnectingClient's re-dial path)."""
        (total,) = _HDR.unpack(_recv_exact(self._sock, 4))
        if total <= self._INLINE_FRAME_MAX:
            body = memoryview(bytearray(total))
            _recv_into_exact(self._sock, body)
            (elen,) = _HDR.unpack(body[:4])
            envelope = msgpack.unpackb(body[4: 4 + elen])
            payload = bytes(body[4 + elen:])
            slot = self._peek_slot(envelope)
            sink = slot.get("sink") if slot is not None else None
            if sink is not None and not envelope.get("e") and len(payload) > 4:
                # Tiny chunk (single-recv frame): honor the sink contract
                # with an explicit copy so callers see a uniform API.
                (mlen,) = _HDR.unpack(payload[:4])
                rest = len(payload) - 4 - mlen
                if rest == sink.nbytes and rest > 0:
                    sink[:] = memoryview(payload)[4 + mlen:]
                    slot["sunk"] = rest
                    payload = payload[: 4 + mlen]
            return envelope, payload
        (elen,) = _HDR.unpack(_recv_exact(self._sock, 4))
        envelope = msgpack.unpackb(_recv_exact(self._sock, elen))
        plen = total - 4 - elen
        slot = self._peek_slot(envelope)
        sink = slot.get("sink") if slot is not None else None
        if sink is not None and not envelope.get("e") and plen > 4:
            # Sink framing: [4B meta len][meta][chunk]. When the chunk part
            # is exactly the sink's size, it is received in place and the
            # returned payload carries only the meta prefix.
            hdr = _recv_exact(self._sock, 4)
            (mlen,) = _HDR.unpack(hdr)
            meta = _recv_exact(self._sock, min(mlen, plen - 4))
            rest = plen - 4 - len(meta)
            if rest == sink.nbytes:
                _recv_into_exact(self._sock, sink)
                slot["sunk"] = rest
                return envelope, hdr + meta
            return envelope, hdr + meta + _recv_exact(self._sock, rest)
        return envelope, _recv_exact(self._sock, plen) if plen else b""

    def _read_loop(self):
        reason = "reader exited"
        try:
            while not self._closed.is_set():
                envelope, payload = self._read_one()
                kind = envelope["k"]
                if kind == "resp":
                    with self._pending_lock:
                        slot = self._pending.pop(envelope["i"], None)
                    if slot is not None:
                        # Drop the sink export NOW: this frame parks in
                        # recv until the next message, and a lingering
                        # memoryview would block the segment's close.
                        slot.pop("sink", None)
                        cb = slot.get("cb")
                        if cb is not None:
                            # Async-call completion: runs ON the reader
                            # thread — callbacks must be quick and must not
                            # block on RPCs over this same client.
                            try:
                                cb(envelope, payload)
                            except Exception:
                                logger.exception("%s async callback failed",
                                                 self._name)
                        else:
                            slot["env"] = envelope
                            slot["payload"] = payload
                            slot["event"].set()
                elif kind == "push":
                    if self._push_handler is not None:
                        self._push_queue.put((envelope["m"], payload))
        except (ConnectionLost, OSError) as e:
            reason = f"{type(e).__name__}: {e}"
        finally:
            if not self._closed.is_set():
                logger.info("%s: connection to %s closed (%s)", self._name,
                            self.address, reason)
            self._closed.set()
            with self._pending_lock:
                pending = list(self._pending.values())
                self._pending.clear()
            for slot in pending:
                slot.pop("sink", None)
                cb = slot.get("cb")
                if cb is not None:
                    try:
                        cb({"e": "connection lost", "_lost": True}, b"")
                    except Exception:
                        logger.exception("%s async callback failed",
                                         self._name)
                else:
                    slot["env"] = {"e": "connection lost", "_lost": True}
                    slot["payload"] = b""
                    slot["event"].set()
            if self.on_close is not None:
                try:
                    self.on_close()
                except Exception:
                    logger.exception("%s on_close callback failed", self._name)

    def call_async(self, method: str, data: Any = None,
                   callback: Optional[Callable[[dict, bytes], None]] = None):
        """Pipelined request: send without waiting for the response.

        With `callback`, it is invoked as callback(envelope, payload) on the
        reader thread when the response (or connection loss: envelope has
        `_lost`) arrives — keep it quick and never block on RPCs over this
        client. Without, the response is dropped (fire-and-forget). This is
        the submission fast path: N tasks cost N sends, not N round trips.
        """
        self._async_send(method, serialization.dumps_ctrl(data), callback)

    def call_raw_async(self, method: str, payload,
                       callback: Callable[[dict, bytes], None]):
        """Pipelined raw-bytes request against a `register_raw` handler:
        `payload` (bytes or a list of buffer parts) travels verbatim — no
        pickle framing on either side. Same callback contract as
        call_async. This is the serve fast lane's transport: a coalesced
        request frame costs one send, and the reply frame's bytes reach
        the callback without an intermediate decode."""
        self._async_send(method, payload, callback)

    def _async_send(self, method: str, payload,
                    callback: Optional[Callable[[dict, bytes], None]]):
        """Shared pipelined-send core: pending-slot registration, the
        closed-between-check-and-insert drain race, and the OSError
        double-delivery guard live HERE once — both async entry points
        differ only in payload framing."""
        if self._closed.is_set():
            raise ConnectionLost(
                f"{self._name}: connection to {self.address} is closed")
        msg_id = next(self._msg_counter)
        if callback is not None:
            with self._pending_lock:
                self._pending[msg_id] = {"cb": callback}
            if self._closed.is_set():
                # Connection died between the check above and the slot
                # insert: the reader's drain may have missed this slot, so
                # deliver the loss ourselves (pop decides the winner).
                with self._pending_lock:
                    slot = self._pending.pop(msg_id, None)
                if slot is not None:
                    callback({"e": "connection lost", "_lost": True}, b"")
                return
        env = {"i": msg_id, "k": "req", "m": method}
        if _tracing._ENABLED:
            t = _tracing.wire_ctx()
            if t is not None:
                env["t"] = t
        if _CHAOS_FILTER is not None:
            act = _chaos_action(self, method)
            if isinstance(act, tuple) and act and act[0] == "delay":
                time.sleep(act[1])
            elif act == "drop":
                # Blackhole: the send is swallowed. A pipelined caller's
                # callback gets the loss envelope (on an ordered stream a
                # silent drop and a dead connection look identical to the
                # sender); without a callback it is fire-and-forget anyway.
                with self._pending_lock:
                    slot = self._pending.pop(msg_id, None)
                if callback is not None and slot is not None:
                    callback({"e": "chaos: dropped", "_lost": True}, b"")
                return
            elif act == "error":
                with self._pending_lock:
                    self._pending.pop(msg_id, None)
                raise ConnectionLost(
                    f"{self._name}: chaos fault injected on '{method}'")
        try:
            _send_msg(self._sock, env, payload, self._send_lock)
        except OSError as e:
            self._closed.set()
            with self._pending_lock:
                slot = self._pending.pop(msg_id, None)
            if callback is not None and slot is None:
                # The reader's drain already delivered the loss to the
                # callback; raising here would make the caller (e.g.
                # ReconnectingClient) resend with the same callback and
                # fire it twice.
                return
            raise ConnectionLost(str(e))

    def _call_framed(self, method: str, payload,
                     timeout: Optional[float],
                     sink: Optional[memoryview] = None) -> Tuple[bytes, int]:
        """Send one request payload (bytes or buffer parts) and block for
        the raw response payload. Shared by call()/call_raw(). With
        `sink`, a response whose chunk part matches the sink's size is
        received directly into it; returns (payload, bytes_sunk)."""
        if self._closed.is_set():
            raise ConnectionLost(f"{self._name}: connection to {self.address} is closed")
        msg_id = next(self._msg_counter)
        slot = {"event": threading.Event()}
        if sink is not None:
            slot["sink"] = sink
        with self._pending_lock:
            self._pending[msg_id] = slot
        env = {"i": msg_id, "k": "req", "m": method}
        if _tracing._ENABLED:
            t = _tracing.wire_ctx()
            if t is not None:
                env["t"] = t
        suppress_send = False
        if _CHAOS_FILTER is not None:
            act = _chaos_action(self, method)
            if isinstance(act, tuple) and act and act[0] == "delay":
                time.sleep(act[1])
            elif act == "error":
                with self._pending_lock:
                    self._pending.pop(msg_id, None)
                raise ConnectionLost(
                    f"{self._name}: chaos fault injected on '{method}'")
            elif act == "drop":
                # Blackhole: skip the send; the slot wait below delivers
                # this caller's own bounded TimeoutError.
                suppress_send = True
        if not suppress_send:
            try:
                _send_msg(self._sock, env, payload, self._send_lock)
            except OSError as e:
                self._closed.set()
                raise ConnectionLost(str(e))
        if not slot["event"].wait(timeout or GLOBAL_CONFIG.rpc_call_timeout_s):
            with self._pending_lock:
                self._pending.pop(msg_id, None)
            raise TimeoutError(f"{self._name}: RPC '{method}' to {self.address} timed out")
        env = slot["env"]
        if env.get("_lost"):
            # The connection died with this request in flight: typed as a
            # transport failure so reconnecting callers retry.
            raise ConnectionLost(
                f"{self._name}: connection lost during RPC '{method}'")
        if env.get("e"):
            raise RaySystemError(f"RPC '{method}' failed remotely: {env['e']}")
        return slot["payload"], slot.get("sunk", 0)

    def call(self, method: str, data: Any = None, timeout: Optional[float] = None) -> Any:
        payload, _ = self._call_framed(method, serialization.dumps_ctrl(data), timeout)
        return serialization.loads(payload) if payload else None

    def call_raw(self, method: str, payload,
                 timeout: Optional[float] = None) -> bytes:
        """Raw-bytes RPC against a `register_raw` server handler: the
        request payload (bytes or a list of buffer parts) travels verbatim
        — no pickle on either side — and the handler's raw reply bytes are
        returned. Safe to call concurrently from many threads: message ids
        multiplex the in-flight requests, which is how the transfer plane
        keeps a window of chunk fetches pipelined on one connection."""
        out, _ = self._call_framed(method, payload, timeout)
        return out

    def call_raw_into(self, method: str, payload, sink: memoryview,
                      timeout: Optional[float] = None) -> Tuple[bytes, int]:
        """call_raw whose response chunk part is received DIRECTLY into
        `sink` (a writable memoryview) when its size matches — the
        receive-side half of zero-copy transfer. Returns (meta payload,
        bytes written into sink); 0 means the reply didn't match the sink
        (busy/missing/short) and any chunk bytes are in the payload."""
        return self._call_framed(method, payload, timeout, sink=sink)

    def close(self):
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class ReconnectingClient:
    """RPC client that re-dials on connection loss (one retry per call).

    The GCS link must survive transient drops — GCS fault tolerance lets
    raylets and workers reconnect after a GCS restart (reference
    `gcs_failover_worker_reconnect_timeout`); this is the client half. The
    optional `resubscribe` callback re-establishes per-connection state
    (pubsub subscriptions, node registration) on the fresh connection.
    """

    def __init__(self, address: str, name: str, push_handler=None,
                 resubscribe=None, resolve=None):
        self.address = address
        self._name = name
        self._push_handler = push_handler
        self._resubscribe = resubscribe
        # Optional address provider, consulted before EVERY dial attempt:
        # a client that cached its address while the server was down (e.g.
        # a GCS killed and restarted elsewhere) re-resolves instead of
        # re-dialing the dead endpoint forever.
        self._resolve = resolve
        self._lock = threading.Lock()
        self._terminal = False  # close() is final: no resurrection
        self._client = RpcClient(address, name=name, push_handler=push_handler)

    @property
    def is_closed(self) -> bool:
        return self._terminal or self._client.is_closed

    def wait_disconnected(self, timeout: Optional[float] = None) -> bool:
        """Block until the underlying connection is observed closed (e.g.
        a test killed the server and must not proceed on a fixed sleep).
        True when the loss was seen within `timeout`."""
        return self._client._closed.wait(timeout)

    def _reconnect(self) -> RpcClient:
        # Bounded-backoff re-dial: each attempt re-resolves the address
        # and dials with a short per-attempt timeout, so a server that
        # comes back mid-outage (GCS restart) is picked up quickly while
        # the overall wait stays bounded by gcs_reconnect_timeout_s (a
        # dead server fails the call with ConnectionLost, never hangs
        # it). Dial attempts serialize on the lock (one racer re-dials,
        # the rest adopt its fresh client); backoff sleeps run OUTSIDE
        # the lock (RL002).
        deadline = time.monotonic() + GLOBAL_CONFIG.gcs_reconnect_timeout_s
        backoff = 0.05
        last_err: Optional[Exception] = None
        while True:
            with self._lock:
                if self._terminal:
                    raise ConnectionLost(f"{self._name}: client closed")
                if not self._client.is_closed:
                    return self._client
                addr = self.address
                if self._resolve is not None:
                    try:
                        addr = self._resolve() or self.address
                    except Exception:  # noqa: BLE001 — fall back to cached
                        logger.debug("%s: address re-resolve failed",
                                     self._name, exc_info=True)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ConnectionLost(
                        f"{self._name}: reconnect to {addr} timed out "
                        f"after {GLOBAL_CONFIG.gcs_reconnect_timeout_s}s"
                    ) from last_err
                try:
                    client = RpcClient(
                        addr, name=self._name,
                        push_handler=self._push_handler,
                        connect_timeout=min(max(backoff * 2, 0.2),
                                            remaining))
                except ConnectionLost as e:
                    client = None
                    last_err = e
                if client is not None:
                    self.address = addr
                    self._client = client
                    if self._resubscribe is not None:
                        try:
                            self._resubscribe(self._client)
                        except Exception:
                            logger.warning("%s: resubscribe failed",
                                           self._name)
                    return self._client
            time.sleep(min(backoff, max(0.0, deadline - time.monotonic())))
            backoff = min(backoff * 2, 1.0)

    def call(self, method: str, data: Any = None, timeout: Optional[float] = None):
        if self._terminal:
            # A racing in-flight call must not re-dial after an intentional
            # close — e.g. a stopped raylet's heartbeat would re-register
            # the dead node with the GCS as ALIVE.
            raise ConnectionLost(f"{self._name}: client closed")
        try:
            return self._client.call(method, data, timeout=timeout)
        except ConnectionLost:
            client = self._reconnect()
            return client.call(method, data, timeout=timeout)

    def call_async(self, method: str, data: Any = None, callback=None):
        """Pipelined send (see RpcClient.call_async); re-dials once."""
        if self._terminal:
            raise ConnectionLost(f"{self._name}: client closed")
        try:
            return self._client.call_async(method, data, callback)
        except ConnectionLost:
            client = self._reconnect()
            return client.call_async(method, data, callback)

    def close(self):
        self._terminal = True
        self._client.close()


def find_free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port
