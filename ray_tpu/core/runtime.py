"""CoreRuntime: the embedded runtime of every driver and worker process.

Equivalent of the reference's CoreWorker (`src/ray/core_worker/core_worker.h:284`):
task submission with spillback retry (`direct_task_transport.h`), object
put/get against the node store + inline fast path, `wait`, actor handle
management and the direct actor transport with per-caller ordering
(`direct_actor_task_submitter.h`), task retries, and owner-side object
lifetime (frees propagate to the directory on ref drop).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import threading
import time
from collections import OrderedDict, defaultdict
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.common import TaskSpec
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_store import ObjectStoreClient, _segment_name
from ray_tpu.core.rpc import ConnectionLost, ReconnectingClient, RpcClient
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    RayActorError,
    RaySystemError,
    RayTaskError,
    TaskCancelledError,
)

logger = logging.getLogger(__name__)

_PENDING = object()


# --- Parked-operation registry (chaos zero-hangs watchdog) -------------------
# Every potentially-unbounded blocking wait in the public API (get / wait /
# actor resolution) registers itself here for its duration. The chaos
# plane's HangWatchdog samples the registry to enforce "no parked future
# outlives the recovery deadline": a hang becomes an attributed assertion
# (which op, for how long) instead of a silent wedge. Cost when nobody
# watches: one dict insert + delete per blocking call.

_parked_ops: Dict[int, Tuple[str, float]] = {}
_parked_lock = threading.Lock()
_parked_counter = 0


class _ParkedOp:
    __slots__ = ("token",)

    def __init__(self, desc: str):
        global _parked_counter
        with _parked_lock:
            _parked_counter += 1
            self.token = _parked_counter
            _parked_ops[self.token] = (desc, time.monotonic())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        with _parked_lock:
            _parked_ops.pop(self.token, None)
        return False


def parked_ops() -> List[Tuple[int, str, float]]:
    """(token, description, seconds parked) for every blocking public-API
    op currently in flight in THIS process."""
    now = time.monotonic()
    with _parked_lock:
        return [(tok, desc, now - t0)
                for tok, (desc, t0) in _parked_ops.items()]


class _TaskRecord:
    __slots__ = ("event", "results", "error", "crashed", "spec", "attempts",
                 "reconstructions", "submitted_addr")

    def __init__(self, spec: Optional[TaskSpec] = None):
        self.event = threading.Event()
        self.results: Optional[List[Dict[str, Any]]] = None
        self.error: Optional[bytes] = None
        self.crashed = False
        self.spec = spec
        self.attempts = 0
        self.reconstructions = 0  # lineage re-executions after object loss
        self.submitted_addr: Optional[str] = None  # raylet holding the task


class ActorClient:
    """Direct connection to an actor's worker (per caller, ordered)."""

    def __init__(self, runtime: "CoreRuntime", actor_id: ActorID, address: str):
        self.actor_id = actor_id
        self.address = address
        self.seq = 0
        # Held across seq assignment + send so the wire order matches seq
        # order even with concurrent submitters.
        self.lock = threading.Lock()
        self.client = RpcClient(
            address, name=f"actor-{actor_id.hex()[:8]}",
            push_handler=runtime._on_raylet_push,
            on_close=lambda: runtime._on_actor_conn_lost(actor_id))


class CoreRuntime:
    def __init__(
        self,
        gcs_address: str,
        raylet_address: str,
        session_suffix: str,
        node_id: Optional[NodeID] = None,
        job_id: Optional[JobID] = None,
        worker_id: Optional[WorkerID] = None,
        is_driver: bool = True,
        namespace: str = "default",
    ):
        self.is_driver = is_driver
        self.worker_id = worker_id or WorkerID.from_random()
        self.namespace = namespace
        self.node_id = node_id
        self.gcs = ReconnectingClient(gcs_address, name="runtime->gcs",
                                      push_handler=self._on_gcs_push,
                                      resubscribe=self._resubscribe_gcs)
        self.raylet = RpcClient(raylet_address, name="runtime->raylet",
                                push_handler=self._on_raylet_push)
        self.store = ObjectStoreClient(session_suffix)
        self.session_suffix = session_suffix
        from ray_tpu.core.object_store import SegmentPool

        self._segment_pool = SegmentPool(
            session_suffix, GLOBAL_CONFIG.segment_pool_max_bytes)
        if job_id is None:
            resp = self.gcs.call(
                "register_job",
                {"pid": os.getpid(), "namespace": namespace,
                 "entrypoint": " ".join(os.sys.argv),
                 # Set by the job agent for submitted-job drivers: links
                 # this driver job to its submission record (job-tier
                 # status, tenant QoS, job-scoped cleanup).
                 "submission_id": os.environ.get("RAY_TPU_SUBMISSION_ID",
                                                 "")})
            job_id = resp["job_id"]
        self.job_id = job_id
        # Job-level runtime_env: a submitted driver inherits its job's
        # prepared runtime_env (RAY_TPU_JOB_RUNTIME_ENV, set by the job
        # agent) as the default for every task/actor it submits — that's
        # what routes the job's tasks to its per-env forge workers. A
        # worker inherits the prepared-URI subset riding its own grant
        # (RAY_TPU_RUNTIME_ENV), so nested tasks stay in the job's env.
        _renv_blob = os.environ.get("RAY_TPU_JOB_RUNTIME_ENV") \
            or os.environ.get("RAY_TPU_RUNTIME_ENV")
        try:
            self._job_runtime_env = json.loads(_renv_blob) \
                if _renv_blob else None
        except ValueError:
            self._job_runtime_env = None
        # The "driver task" context: puts and submissions hang off this id.
        self.current_task_id = TaskID.for_task(job_id)
        self._put_counter = 0
        # Process-wide count of lineage re-executions (_try_reconstruct
        # resubmits): the data plane's recomputed-block accounting reads
        # deltas of this to prove recovery after a node death is bounded.
        self.reconstructions_total = 0
        self._lock = threading.RLock()
        self._tasks: Dict[bytes, _TaskRecord] = {}          # task_id -> record
        self._object_to_task: Dict[bytes, bytes] = {}        # return oid -> task_id
        # Retained lineage of freed objects (task_key -> retained bytes):
        # specs stay re-executable after their outputs are freed, bounded
        # by lineage_max_bytes (oldest evicted first; see _retire_lineage).
        self._retired_lineage: "OrderedDict[bytes, int]" = OrderedDict()
        self._retired_lineage_bytes = 0
        self._object_cache: Dict[bytes, Any] = {}            # oid -> deserialized value
        self._exported_functions: set = set()
        self._actor_clients: Dict[bytes, ActorClient] = {}
        self._actor_states: Dict[bytes, Dict[str, Any]] = {}
        self._env_cache = None  # lazy runtime_env.EnvCache
        self._actor_events: Dict[bytes, threading.Event] = defaultdict(threading.Event)
        # Actor ids whose register_actor this runtime pipelined and whose
        # first state push hasn't landed yet (see create_actor /
        # wait_for_actor: suppresses the per-poll directory query).
        self._created_pending: set = set()
        self._raylet_clients: Dict[str, RpcClient] = {raylet_address: self.raylet}
        # addr -> monotonic time of last failed dial (see _raylet_for);
        # entries expire after _DEAD_DIAL_TTL_S and are pruned inline.
        self._raylet_dial_failures: Dict[str, float] = {}
        # By-value argument dedupe cache (see serialize_args): LRU of
        # (type, value) -> serialized blob, hard-capped by
        # arg_dedupe_cache_entries (evicted oldest-first on insert).
        self._arg_blob_cache: "OrderedDict" = OrderedDict()
        self._free_buffer: List[ObjectID] = []
        self._free_timer: Optional[threading.Timer] = None
        self._bg_executor = None  # lazy ThreadPoolExecutor for resubmits
        from ray_tpu.core.direct_task import DirectTaskTransport

        self._direct = DirectTaskTransport(self)
        # Actor-call inline results ride the direct push channel and are
        # NOT in the cluster object directory; when such a ref is passed as
        # a task argument it must be published first (lazily — most actor
        # results never leave the caller). Keys are published-or-pending.
        self._published_deps: set = set()
        self._publish_when_done: set = set()
        # Owner-side reference counting (reference `reference_count.h`):
        # local ObjectRef count per object + pins while submitted tasks
        # depend on the object; frees are deferred until both drop to zero.
        self._ref_counts: Dict[bytes, int] = defaultdict(int)
        self._dep_pins: Dict[bytes, int] = defaultdict(int)
        self._deferred_free: set = set()
        # Borrower protocol (reference reference_count.h:61,494-500):
        # objects this process OWNS (it may free them on last drop) vs
        # objects it merely BORROWS (deserialized refs — last drop removes
        # this process from the GCS borrower set instead of freeing).
        self._owned_puts: set = set()
        self._borrowed: set = set()
        # Event-driven object availability: the raylet pushes
        # object_ready/object_unavailable instead of this process polling.
        # oid -> [Event, refcount]; refcounted so concurrent getters of the
        # same object share wakeups and the entry outlives the first getter.
        self._object_events: Dict[bytes, list] = {}
        # Event-driven wait(): each active wait() registers a
        # (deque, Event) watcher; completions append the finished task key
        # (None = non-task object progress) and set the event, so waiters
        # re-check only the refs that just completed instead of rescanning
        # every pending ref per wake (which made wait on 1k refs O(n^2)).
        self._wait_watchers: List[tuple] = []
        # get_future(): task key -> [resolve callbacks]; drained on task
        # completion into the lazily-created resolver pool (async callers
        # — the Serve proxy — await values without parking a thread per
        # in-flight request).
        self._future_waiters: Dict[bytes, List[Any]] = {}
        self._future_pool = None
        self._closed = False
        # Worker-side execution context (set by worker loop while running)
        self.executing_task: Optional[TaskSpec] = None
        # Span propagation (reference tracing_helper.py:35-81) lives in
        # the process-global tracing module (ray_tpu.observability): the
        # context of the currently-executing task flows into child
        # submissions, RPC framing, and spans. Re-read the tracing flags
        # here so workers pick them up from the propagated env.
        from ray_tpu.observability import tracing as _tracing_mod

        _tracing_mod.refresh_from_config()
        # Metrics flush: user Counters/Gauges/Histograms in this process
        # surface at the GCS (rendered by /metrics on the dashboard);
        # trace spans from the flight recorder piggyback on the same
        # cadence. `node` lets the GCS expire this reporter when the
        # owning node dies.
        from ray_tpu.util.metrics import MetricsPusher

        self._metrics_pusher = MetricsPusher(
            self.gcs, reporter_id=("driver-" if is_driver else "worker-")
            + self.worker_id.hex()[:12],
            node=node_id.hex() if node_id is not None else None)
        self._metrics_pusher.start()
        # Drivers receive worker stdout/stderr over the LOG channel
        # (reference log_to_driver).
        if is_driver and GLOBAL_CONFIG.log_to_driver:
            try:
                self.gcs.call("subscribe", {"channel": "LOG", "key": b"*"},
                              timeout=5)
            except Exception:  # noqa: BLE001
                pass

    # ----------------------------------------------------------- push events

    def _on_raylet_push(self, method: str, data: Any):
        if method == "task_dep_lost":
            # A raylet found every copy of a dependency gone while one of
            # our tasks was parked on it. We own the creating task, so
            # re-execute it (idempotent: an in-flight reconstruction is
            # reused); the raylet's lost-dep ladder re-pulls as soon as
            # the re-executed object registers. Off the push thread: the
            # reconstruction may recursively rebuild deps.
            oid: ObjectID = data["object_id"]
            threading.Thread(target=self._try_reconstruct, args=(oid,),
                             name="dep-reconstruct", daemon=True).start()
            return
        if method == "task_result_batch":
            # Coalesced lease-worker completions (normally unrolled by the
            # direct transport's push handler; kept here so ANY connection
            # delivering a batch resolves correctly).
            for item in data["batch"]:
                self._on_raylet_push("task_result", item)
            return
        if method == "task_result":
            task_id: TaskID = data["task_id"]
            with self._lock:
                rec = self._tasks.get(task_id.binary())
            if rec is None:
                return
            if rec.event.is_set():
                # Already terminally resolved (e.g. failed by the actor-death
                # path): a late raylet notification must not unpin deps a
                # second time or resubmit the failed task.
                return
            if data.get("crashed") and rec.spec is not None and \
                    rec.attempts < rec.spec.max_retries:
                rec.attempts += 1
                logger.warning("retrying task %s (attempt %d/%d)", rec.spec.name,
                               rec.attempts, rec.spec.max_retries)
                threading.Thread(target=self._submit_spec, args=(rec.spec,),
                                 daemon=True).start()
                return
            rec.results = data.get("results") or []
            rec.error = data.get("error")
            rec.crashed = bool(data.get("crashed"))
            if rec.spec is not None:
                self._unpin_deps(rec.spec)
            for r in rec.results:
                if r["kind"] == "inline":
                    rkey = r["object_id"].binary()
                    if rkey not in self._object_to_task:
                        continue  # all refs already dropped; don't cache
                    try:
                        self._object_cache[rkey] = \
                            serialization.deserialize(r["data"])
                    except Exception as e:
                        rec.error = serialization.serialize_exception(
                            RaySystemError(f"result deserialization failed: {e}"))
            if rec.error is not None and rec.spec is not None:
                # Materialize the error as the task's return objects so tasks
                # elsewhere that depend on them get scheduled and re-raise
                # (reference: error objects stored in the object store).
                for oid in rec.spec.return_ids():
                    try:
                        self.gcs.call("object_location_add",
                                      {"object_id": oid, "inline": rec.error,
                                       "size": len(rec.error)}, timeout=10)
                    except Exception:  # noqa: BLE001 — rec.event below still
                        # unblocks local waiters with the error
                        logger.debug("error publication for %s failed", oid,
                                     exc_info=True)
            rec.event.set()
            # Deferred publication: a ref of this (actor) task was passed
            # as a task dependency before the result arrived. Runs after
            # event.set() so _ensure_dep_visible's is_set() check plus the
            # locked set-pop below give exactly-once publication.
            if rec.spec is not None and rec.results and \
                    (rec.spec.actor_id is not None or rec.spec.direct):
                with self._lock:
                    pending = [r for r in rec.results
                               if r["object_id"].binary()
                               in self._publish_when_done]
                    for r in pending:
                        self._publish_when_done.discard(
                            r["object_id"].binary())
                if pending:
                    self._publish_inline_results(pending)
            self._notify_waiters(task_id.binary())
        elif method == "task_respill":
            # A raylet returned a queued task it can never run (the cluster
            # grew): resubmit through the normal routing path.
            spec = data["spec"]
            from ray_tpu.core.direct_task import LEASE_SPEC_NAME

            if spec.name == LEASE_SPEC_NAME:
                self._direct.on_lease_respill(spec)
            else:
                threading.Thread(target=self._resubmit_respilled,
                                 args=(spec,), daemon=True).start()
        elif method == "lease_granted":
            self._direct.on_lease_granted(data)
        elif method in ("object_ready", "object_unavailable"):
            entry = self._object_events.get(data["object_id"].binary())
            if entry is not None:
                entry[0].set()
            self._notify_waiters(None)
        elif method == "cancel_exec":
            self.on_cancel_exec(data["task_id"])
        elif method == "execute_task":
            # Only workers receive this; WorkerLoop overrides via subclassing hook.
            self.on_execute_task(data["spec"])

    def on_execute_task(self, spec: TaskSpec):  # overridden in worker.py
        raise RaySystemError("driver runtime received execute_task")

    def on_cancel_exec(self, task_id):  # overridden in worker.py
        pass

    def _resubscribe_gcs(self, client: RpcClient):
        # Re-bind this driver's job to the fresh connection so driver-exit
        # cleanup still fires after a GCS failover.
        if self.is_driver and getattr(self, "job_id", None) is not None:
            try:
                client.call("reattach_job", {"job_id": self.job_id}, timeout=5)
            except Exception:  # noqa: BLE001 — older GCS or racing restart
                pass
        if self.is_driver and GLOBAL_CONFIG.log_to_driver:
            try:
                client.call("subscribe", {"channel": "LOG", "key": b"*"},
                            timeout=5)
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            actor_keys = [k for k in self._actor_clients] + \
                [k for k in self._actor_states]
        for key in set(actor_keys):
            client.call("subscribe", {"channel": "ACTOR", "key": key}, timeout=5)

    def _on_gcs_push(self, method: str, data: Any):
        if method == "pubsub_batch":
            # Delta-batched pubsub frame (GCS coalesces per subscriber):
            # unroll in arrival order — within a batch the GCS preserved
            # publish order per key.
            for ev in data.get("events", ()):
                self._on_gcs_push("pubsub", ev)
            return
        if method != "pubsub":
            return
        if data["channel"] == "LOG":
            from ray_tpu.core.log_streaming import print_log_batch

            msg = data["message"]
            # Only this driver's job (untagged output — actor background
            # threads between tasks — still prints).
            if msg.get("job") in (None, self.job_id.hex()):
                print_log_batch(msg)
            return
        if data["channel"] == "ACTOR":
            actor_key = data["key"]
            with self._lock:
                self._actor_states[actor_key] = data["message"]
                self._created_pending.discard(actor_key)
                self._actor_events[actor_key].set()
                client = self._actor_clients.get(actor_key)
                if client is not None and data["message"].get("state") != "ALIVE":
                    self._actor_clients.pop(actor_key, None)
                    client.client.close()

    # ------------------------------------------------------------------- put

    def put(self, value: Any, _owner: Optional[str] = None) -> ObjectID:
        with self._lock:
            self._put_counter += 1
            oid = ObjectID.for_put(self.current_task_id, self._put_counter)
        self.put_with_id(oid, value)
        return oid

    def put_with_id(self, oid: ObjectID, value: Any):
        from ray_tpu.object_ref import _NestedRefCapture

        with self._lock:
            self._owned_puts.add(oid.binary())
        with _NestedRefCapture() as captured:
            parts = serialization.serialize(value)
        if captured:
            self._register_container_refs(oid, captured)
        size = serialization.serialized_size(parts)
        if size <= GLOBAL_CONFIG.object_inline_max_bytes:
            blob = b"".join(bytes(p) if isinstance(p, memoryview) else p for p in parts)
            self.gcs.call("object_location_add",
                          {"object_id": oid, "inline": blob, "size": size,
                           "owner": self.worker_id.hex()})
            self._object_cache[oid.binary()] = value
        else:
            self._write_segment(oid, parts, size, reusable=True)
            self.raylet.call("object_sealed",
                             {"object_id": oid, "size": size,
                              "owner": self.worker_id.hex()})

    # ------------------------------------------- raw objects (collective)

    def put_raw(self, parts) -> ObjectID:
        """Seal raw bytes as an object with NO serialization framing.

        The segment content is exactly the caller's bytes, so peers pull
        it over the chunked transfer plane and land it with zero
        encode/decode cost — the host-collective plane's data path. Only
        readable back via :meth:`get_raw` (a normal ``get`` would try to
        unpickle the payload)."""
        if not isinstance(parts, (list, tuple)):
            parts = [parts]
        views = [p if isinstance(p, memoryview) else memoryview(p)
                 for p in parts]
        size = sum(v.nbytes for v in views)
        with self._lock:
            self._put_counter += 1
            oid = ObjectID.for_put(self.current_task_id, self._put_counter)
            self._owned_puts.add(oid.binary())
        self._write_segment(oid, views, size, reusable=True)
        self.raylet.call("object_sealed",
                         {"object_id": oid, "size": size,
                          "owner": self.worker_id.hex()})
        return oid

    def get_raw(self, oid: ObjectID,
                timeout: Optional[float] = None) -> memoryview:
        """Raw segment view of a :meth:`put_raw` object, pulled to this
        node via the transfer plane when remote. The view aliases the
        shared segment — consume it before the object is freed."""
        deadline = time.monotonic() + timeout if timeout is not None else None
        buf = self.store.get_buffer(oid)
        if buf is not None:
            return buf
        status, data = self._fetch_via_raylet(oid, deadline)
        if status == "local":
            buf = self.store.get_buffer(oid)
            if buf is not None:
                return buf
        elif status == "inline":
            return memoryview(data)
        if deadline is not None and time.monotonic() >= deadline:
            raise GetTimeoutError(f"Timed out getting raw object {oid}")
        raise ObjectLostError(oid)

    def free_raw(self, oids: Sequence[ObjectID]) -> None:
        """Owner-side free of put_raw objects (no ObjectRef is ever minted
        for them, so the refcount path doesn't apply); batched through the
        normal directory free."""
        with self._lock:
            for oid in oids:
                self._owned_puts.discard(oid.binary())
        for oid in oids:
            self.free_ref(oid)

    def _register_container_refs(self, container: ObjectID, captured):
        """A put/return value embeds ObjectRefs: register the inner ids as
        borrows held by the CONTAINER itself (synthetic borrower
        ``obj:<hex>``, released by the GCS when the container's entry is
        freed — see GcsServer._cascade_container_borrows_locked), so the
        inner objects survive the producer dropping its own refs before any
        consumer deserializes the container. Registered synchronously while
        the producer's refs are still live, so the handoff cannot race the
        inner objects' free (reference: contained-object-id capture in
        `_private/serialization.py` / `reference_count.h`)."""
        seen, inner = set(), []
        for n in captured:
            if n.binary() in seen or n == container:
                continue
            seen.add(n.binary())
            inner.append(n)
            self._ensure_dep_visible(n)
        if not inner:
            return
        try:
            self.gcs.call("borrow_add",
                          {"object_ids": inner,
                           "borrower_id": "obj:" + container.hex()},
                          timeout=10)
        except Exception:  # noqa: BLE001 — worst case: inner objects leak
            pass           # until job end, never a premature free

    def _write_segment(self, oid: ObjectID, parts, size: int,
                       reusable: bool = False):
        """reusable: this process owns the object (a put, not a task
        return written on the owner's behalf) and may recycle the warm
        segment through its SegmentPool when the last reference drops."""
        from multiprocessing import shared_memory

        from ray_tpu._native import gather_copy

        from ray_tpu.core.object_store import _promote_segment, _writer_name

        final = _segment_name(self.session_suffix, oid)
        shm = None
        if reusable:
            shm = self._segment_pool.acquire(oid, size)
        if shm is not None:
            # Warm pooled segment: pages pre-faulted at reclaim time, the
            # copy runs at memcpy speed (cold tmpfs writes fault+zero
            # every page and run 3-5x slower). Acquired under the STAGING
            # name (it holds the previous object's bytes until the copy
            # lands); promoted to the final name only once complete.
            ok = False
            try:
                gather_copy(shm.buf[:size], parts)
                _promote_segment(shm, final)
                ok = True
            finally:
                shm.close()
                if not ok:
                    # Same cleanup as the cold path: a failed copy must
                    # not leak the staged file (a later create of this
                    # object would FileExistsError on the staging name).
                    try:
                        shm.unlink()
                    except OSError:
                        pass
            self._segment_pool.track(oid, size)
            return
        shm = shared_memory.SharedMemory(
            name=_writer_name(self.session_suffix, oid), create=True,
            size=max(size, 1))
        ok = False
        try:
            gather_copy(shm.buf[:size], parts)
            # Atomic publish: same-node readers attach by the final name,
            # which must never exist with incomplete bytes behind it.
            _promote_segment(shm, final)
            ok = True
        finally:
            shm.close()
            from ray_tpu.core.object_store import _untrack
            _untrack(shm)
            if not ok:
                try:
                    shm.unlink()  # drop the staged partial, never leak it
                except OSError:
                    pass
        if reusable:
            self._segment_pool.track(oid, size)

    # ------------------------------------------------------ task submission

    def export_function(self, blob: bytes) -> str:
        fn_id = hashlib.sha1(blob).hexdigest()
        if fn_id not in self._exported_functions:
            self.gcs.call("kv_put", {"namespace": "fn", "key": fn_id.encode(),
                                     "value": blob, "overwrite": False})
            self._exported_functions.add(fn_id)
        return fn_id

    # Immutable leaf types whose serialized form may be deduped across
    # submissions (they cannot embed ObjectRefs, so skipping the
    # nested-ref capture for them is sound). bool before int matters not:
    # the cache key carries the exact type.
    _ARG_CACHE_TYPES = (str, bytes, int, float, bool, type(None))

    def serialize_args(self, args: Sequence[Any], kwargs: Dict[str, Any]
                       ) -> Tuple[List[Tuple[str, Any]], List[str],
                                  List[ObjectID]]:
        """Inline small args; promote large ones to the store; pass refs
        through. Refs nested inside argument values are captured during
        pickling: the spec carries them (`nested_refs`) so the owner pins
        them until the executing worker has registered its borrow.

        Shared by-value args serialize ONCE per owner: small immutable
        leaves hit an LRU blob cache keyed by (type, value), so a loop
        submitting the same literals 10k times pays 10k dict hits, not
        10k pickles (the per-spec arg re-serialization that made
        many-arg tasks lag plain ones)."""
        from ray_tpu.object_ref import ObjectRef, _NestedRefCapture

        out: List[Tuple[str, Any]] = []
        nested: List[ObjectID] = []
        flat = list(args) + list(kwargs.values())
        cache = self._arg_blob_cache
        cache_cap = GLOBAL_CONFIG.arg_dedupe_cache_entries
        for a in flat:
            if isinstance(a, ObjectRef):
                self._ensure_dep_visible(a.object_id)
                out.append(("r", a.object_id))
                continue
            cache_key = None
            if cache_cap > 0 and type(a) in self._ARG_CACHE_TYPES:
                if type(a) is float:
                    # Floats key by bit pattern: -0.0 == 0.0 (a sign-of-
                    # zero task would get the wrong cached value) and
                    # NaN != NaN (every NaN would miss and pile up).
                    cache_key = (float, struct.pack("<d", a))
                else:
                    cache_key = (type(a), a)
                blob = cache.get(cache_key)
                if blob is not None:
                    cache.move_to_end(cache_key)
                    # "c": dedupe-eligible immutable leaf — the worker may
                    # share ONE deserialized value across tasks.
                    out.append(("c", blob))
                    continue
                # Primitive leaves cannot carry refs: serialize without
                # the capture scope.
                blob = serialization.serialize_to_bytes(a)
            else:
                with _NestedRefCapture() as captured:
                    blob = serialization.serialize_to_bytes(a)
                nested.extend(captured)
            if len(blob) > GLOBAL_CONFIG.object_inline_max_bytes:
                out.append(("r", self.put(a)))
            elif cache_key is not None:
                out.append(("c", blob))
                cache[cache_key] = blob
                while len(cache) > cache_cap:
                    cache.popitem(last=False)
            else:
                out.append(("v", blob))
        for oid in nested:
            self._ensure_dep_visible(oid)
        return out, list(kwargs.keys()), nested

    def _ensure_dep_visible(self, oid: ObjectID):
        """Make an actor-call result usable as a task dependency: publish
        its inline payload to the object directory (once). Normal task
        results are registered by the executing raylet; actor store
        results by the actor's raylet — only actor INLINE results are
        invisible cluster-wide."""
        key = oid.binary()
        with self._lock:
            if key in self._published_deps:
                return
            self._published_deps.add(key)
            task_key = self._object_to_task.get(key)
            rec = self._tasks.get(task_key) if task_key is not None else None
            if rec is None or rec.spec is None or \
                    (rec.spec.actor_id is None and not rec.spec.direct):
                return  # puts/raylet task returns: already directory-visible
            self._publish_when_done.add(key)
        # Race arbitration with the result handler (which publishes pending
        # keys AFTER rec.event.set()): if the event is set here, the
        # handler's scan may have run before our add — whoever pops the key
        # from the set (under the lock) publishes; the other side skips.
        if rec.event.is_set():
            with self._lock:
                claimed = key in self._publish_when_done
                self._publish_when_done.discard(key)
                results = [r for r in (rec.results or [])
                           if r["object_id"].binary() == key]
            if claimed:
                self._publish_inline_results(results)

    def _publish_inline_results(self, results: List[Dict[str, Any]]):
        for r in results:
            if r.get("kind") != "inline":
                continue
            try:
                self.gcs.call("object_location_add",
                              {"object_id": r["object_id"],
                               "inline": r["data"], "size": len(r["data"]),
                               "owner": self.worker_id.hex()}, timeout=10)
            except Exception:  # noqa: BLE001
                logger.warning("failed to publish actor result %s",
                               r["object_id"])

    def child_trace_ctx(self) -> Dict[str, str]:
        """A fresh span context for a task being submitted from this
        context: same trace as the currently-executing task (or a new
        root, head-sampled), with the current span as parent."""
        from ray_tpu.observability import tracing

        return tracing.child_spec_ctx()

    def set_trace_ctx(self, ctx: Optional[Dict[str, str]]):
        from ray_tpu.observability import tracing

        tracing.set_current(ctx)

    def submit_task(self, spec: TaskSpec) -> List[ObjectID]:
        if spec.trace_ctx is None:
            spec.trace_ctx = self.child_trace_ctx()
        spec.runtime_env = self._prepare_runtime_env(spec.runtime_env)
        rec = _TaskRecord(spec=spec)
        return_ids = spec.return_ids()  # minted once: hot-path ids hash
        with self._lock:
            self._tasks[spec.task_id.binary()] = rec
            for oid in return_ids:
                self._object_to_task[oid.binary()] = spec.task_id.binary()
        self._pin_deps(spec)
        if GLOBAL_CONFIG.direct_task_enabled and self._direct.eligible(spec):
            self._direct.submit(spec)
        else:
            self._submit_spec_async(spec)
        return return_ids

    def _submit_spec_async(self, spec: TaskSpec):
        """Pipelined submission: send the spec and return immediately; the
        queued/spillback response is handled on the RPC reader thread.
        Mirrors the reference's async task submission (CoreWorker submits
        without blocking the caller, `direct_task_transport.h`): N
        `.remote()` calls cost N sends, not N round trips."""
        def cb(env, payload):
            if env.get("_lost"):
                # Local raylet died with the submit in flight: the process
                # cannot make progress; fail the record so gets raise.
                self._async_submit_error(
                    spec, RaySystemError("lost connection to raylet"))
                return
            if env.get("e"):
                self._async_submit_error(spec, RaySystemError(
                    f"submit_task failed remotely: {env['e']}"))
                return
            try:
                resp = serialization.loads(payload) if payload else {}
            except Exception as e:  # noqa: BLE001
                self._async_submit_error(spec, RaySystemError(
                    f"bad submit response: {e}"))
                return
            status = resp.get("status")
            if status == "queued":
                rec = self._tasks.get(spec.task_id.binary())
                if rec is not None:
                    rec.submitted_addr = self.raylet.address
            elif status == "spillback":
                # Routing continues with blocking hops — off the reader
                # thread (dialing the spill target must not stall response
                # dispatch for every other in-flight call).
                self._bg_submit(self._continue_spillback, spec,
                                resp["address"])
            else:
                self._async_submit_error(spec, RaySystemError(
                    f"unexpected submit status {resp}"))

        try:
            self.raylet.call_async(
                "submit_task", {"spec": spec, "grant_or_reject": False}, cb)
        except ConnectionLost:
            raise RaySystemError("lost connection to raylet")

    def _bg_submit(self, fn, *args):
        """Run fn(*args) on the shared background executor (lazy)."""
        with self._lock:
            if self._bg_executor is None:
                from concurrent.futures import ThreadPoolExecutor

                self._bg_executor = ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="rt-bg")
            ex = self._bg_executor
        ex.submit(fn, *args)

    def _continue_spillback(self, spec: TaskSpec, address: str):
        if self._closed:
            return
        rec = self._tasks.get(spec.task_id.binary())
        if rec is None or rec.event.is_set():
            return
        try:
            self._submit_spec(spec, start_addr=address, spilled=True)
        except Exception as e:  # noqa: BLE001
            self._async_submit_error(spec, RaySystemError(
                f"spillback resubmit failed: {e}"))

    def _async_submit_error(self, spec: TaskSpec, err: Exception):
        rec = self._tasks.get(spec.task_id.binary())
        if rec is None or rec.event.is_set():
            return
        self._unpin_deps(spec)
        self._fail_task_record(rec, spec,
                               serialization.serialize_exception(err))

    def _submit_spec(self, spec: TaskSpec, start_addr: Optional[str] = None,
                     spilled: bool = False):
        spec.direct = False  # classic path: the raylet registers results
        if start_addr is None or start_addr == self.raylet.address:
            target = self.raylet
            target_addr = self.raylet.address
            spilled = False  # first spillback hop must accept, not bounce
        else:
            target_addr = start_addr
            try:
                target = self._raylet_for(start_addr)
            except ConnectionLost:
                # Spill target already dead (stale view): start locally.
                target = self.raylet
                target_addr = self.raylet.address
                spilled = False
        for _hop in range(8):
            try:
                resp = target.call("submit_task",
                                   {"spec": spec,
                                    "grant_or_reject": spilled})
            except ConnectionLost:
                if target is self.raylet:
                    raise RaySystemError("lost connection to raylet")
                # A spillback target died mid-submit: route through the
                # local raylet again, which may spill to another live node
                # (so grant_or_reject resets — queueing an infeasible task
                # locally would wedge it forever).
                target = self.raylet
                target_addr = self.raylet.address
                spilled = False
                continue
            if resp["status"] == "queued":
                rec = self._tasks.get(spec.task_id.binary())
                if rec is not None:
                    rec.submitted_addr = target_addr
                return
            if resp["status"] == "spillback":
                target_addr = resp["address"]
                try:
                    target = self._raylet_for(target_addr)
                except ConnectionLost:
                    # The node the router chose died between its view
                    # refresh and our dial (a kill can land at any
                    # instant): one transparent re-route via the local
                    # raylet, never a raised submit. Brief pause first —
                    # dead dials now fail in milliseconds (negative
                    # cache), so without it the 8-hop budget can burn
                    # out before the router's node view catches up with
                    # the death we just observed.
                    time.sleep(0.1)
                    target = self.raylet
                    target_addr = self.raylet.address
                spilled = target is not self.raylet
                continue
            raise RaySystemError(f"unexpected submit status {resp}")
        raise RaySystemError("task spillback loop exceeded 8 hops")

    # A failed dial is remembered this long; within the window further
    # dials to the address fail instantly instead of re-running the
    # connect-retry loop (a raylet never restarts on an old address — a
    # new raylet gets a new port — so "recently refused" means dead).
    _DEAD_DIAL_TTL_S = 5.0

    def _raylet_for(self, address: str) -> RpcClient:
        with self._lock:
            client = self._raylet_clients.get(address)
            if client is not None and not client.is_closed:
                return client
            failed_at = self._raylet_dial_failures.get(address)
            if failed_at is not None and \
                    time.monotonic() - failed_at < self._DEAD_DIAL_TTL_S:
                raise ConnectionLost(
                    f"raylet {address} recently unreachable")
        # Dial OUTSIDE the runtime lock: a dead node refuses connects
        # until the dial deadline, and holding the lock through that
        # stalls every other runtime operation (observed: a node kill
        # mid-shuffle wedged the whole driver while reconstruction
        # threads convoyed on one dead spillback target). The short
        # deadline is deliberate — unlike the GCS (which restarts at
        # the same address and deserves the patient retry loop), a
        # refused raylet dial will never start succeeding.
        try:
            client = RpcClient(
                address, name="runtime->raylet-remote",
                connect_timeout=2.0,
                push_handler=self._on_raylet_push,
                on_close=lambda: self._on_remote_raylet_lost(address))
        except ConnectionLost:
            with self._lock:
                now = time.monotonic()
                # Prune expired entries while here: the cache stays
                # bounded by recent churn, not lifetime churn.
                self._raylet_dial_failures = {
                    a: t for a, t in self._raylet_dial_failures.items()
                    if now - t < self._DEAD_DIAL_TTL_S}
                self._raylet_dial_failures[address] = now
            raise
        with self._lock:
            self._raylet_dial_failures.pop(address, None)
            existing = self._raylet_clients.get(address)
            if existing is not None and not existing.is_closed:
                existing_client = existing
            else:
                self._raylet_clients[address] = client
                existing_client = None
        if existing_client is not None:
            # Lost a dial race: keep the first client, drop ours.
            try:
                client.close()
            except Exception:  # noqa: BLE001 — already torn down
                pass
            return existing_client
        return client

    def _resubmit_respilled(self, spec: TaskSpec):
        if self._closed:
            return
        rec = self._tasks.get(spec.task_id.binary())
        if rec is None or rec.event.is_set():
            return  # already resolved elsewhere
        try:
            self._submit_spec(spec)
        except Exception as e:  # noqa: BLE001
            self._fail_task_record(rec, spec, serialization.serialize_exception(
                RaySystemError(f"respill resubmit failed: {e}")))

    def _on_remote_raylet_lost(self, address: str):
        """A remote raylet holding our submitted tasks died: fail over every
        pending task that was queued there by resubmitting through the
        local raylet (which routes around the dead node). Reference: the
        owner's lease tracking resubmits on node failure."""
        if self._closed:
            return
        # Purge the dead client from the address cache (raylint RL012):
        # the entry would otherwise pin a closed RpcClient forever for an
        # address that may never be dialed again — and under 100-node
        # churn those dead entries are one per killed node.
        with self._lock:
            client = self._raylet_clients.get(address)
            if client is not None and client.is_closed:
                self._raylet_clients.pop(address, None)
        # Lease requests queued at the dead raylet die with it: re-route
        # them too (tasks below; leases here).
        self._direct.on_raylet_lost(address)
        with self._lock:
            pending = [rec for rec in self._tasks.values()
                       if rec.submitted_addr == address
                       and rec.spec is not None and not rec.event.is_set()]
        if not pending:
            return
        # Resubmission off the dying client's reader thread: the cluster
        # view is stale right after a node death, so submits may need
        # several attempts while the GCS propagates the update.
        threading.Thread(target=self._failover_tasks,
                         args=(address, pending), daemon=True).start()

    def _failover_tasks(self, address: str, pending: List[_TaskRecord]):
        for rec in pending:
            rec.attempts += 1
            if rec.attempts > rec.spec.max_retries:
                # The user's retry budget (0 = never re-execute a possibly
                # non-idempotent task) governs failover too.
                self._fail_task_record(rec, rec.spec, serialization.serialize_exception(
                    RaySystemError(
                        f"node at {address} died with task {rec.spec.name} "
                        f"(max_retries={rec.spec.max_retries} exhausted)")))
                continue
            logger.warning("raylet %s died; resubmitting task %s "
                           "(attempt %d)", address, rec.spec.name,
                           rec.attempts)
            rec.submitted_addr = None
            last_err: Optional[Exception] = None
            for _try in range(5):
                if self._closed:
                    return
                try:
                    self._submit_spec(rec.spec)
                    last_err = None
                    break
                except Exception as e:  # noqa: BLE001 — stale view, retry
                    last_err = e
                    time.sleep(0.5)
            if last_err is not None:
                self._fail_task_record(rec, rec.spec, serialization.serialize_exception(
                    RaySystemError(f"failover resubmit failed: {last_err}")))

    # -------------------------------------------------------------- actors

    def create_actor(self, spec: TaskSpec) -> ActorID:
        spec.runtime_env = self._prepare_runtime_env(spec.runtime_env)
        key = spec.actor_id.binary()
        # One RPC, subscription piggybacked (the GCS subscribes this
        # connection before scheduling, so the ALIVE publish can't be
        # missed). Named actors stay synchronous: a name conflict must
        # raise HERE (reference semantics). Anonymous creates pipeline —
        # send-and-go, so a burst of N creates costs N sends instead of
        # N serialized GCS round trips; registration failures surface as
        # ActorDiedError on first use via the actor-state machinery.
        if spec.actor_name:
            self.gcs.call("register_actor", {"spec": spec, "subscribe": True})
            return spec.actor_id
        with self._lock:
            self._created_pending.add(key)

        def cb(env, _payload):
            err = env.get("e") or ("GCS connection lost during actor "
                                   "registration" if env.get("_lost") else None)
            if err is None:
                return
            with self._lock:
                self._created_pending.discard(key)
                self._actor_states[key] = {"state": "DEAD", "address": None,
                                           "reason": str(err),
                                           "error_blob": None}
                self._actor_events[key].set()

        self.gcs.call_async("register_actor", {"spec": spec,
                                               "subscribe": True}, cb)
        return spec.actor_id

    def _prepare_runtime_env(self, renv):
        """Local working_dir/py_modules paths -> content-addressed KV URIs
        through the shared memoizing cache (core/runtime_env.EnvCache).
        Tasks without their own runtime_env inherit the job-level one
        (task-level wins outright when both are set)."""
        if not renv:
            renv = self._job_runtime_env
        if not renv or not (renv.get("working_dir") or renv.get("py_modules")
                            or renv.get("pip")):
            return renv
        if self._env_cache is None:
            from ray_tpu.core.runtime_env import EnvCache

            self._env_cache = EnvCache(self.gcs)
        return self._env_cache.prepare(renv)

    def wait_for_actor(self, actor_id: ActorID, timeout: float = 120.0) -> str:
        with _ParkedOp(f"wait_for_actor {actor_id.hex()[:12]}"):
            return self._wait_for_actor(actor_id, timeout)

    def _wait_for_actor(self, actor_id: ActorID, timeout: float) -> str:
        key = actor_id.binary()
        deadline = time.monotonic() + timeout
        # For actors THIS runtime just registered, the subscription rides
        # the register RPC and the ALIVE push is guaranteed to arrive —
        # querying the directory in the wait loop only adds an RPC per
        # 0.5s poll slice per pending actor (an RPC storm during create
        # bursts). Query immediately for foreign actors (named lookups,
        # deserialized handles); for locally-created ones the directory
        # query is anti-entropy after a grace period.
        with self._lock:
            locally_created = key in self._created_pending
        # Foreign actors keep the old 0.5s poll cadence — THIS runtime has
        # no pubsub subscription for them, so the directory query is the
        # only progress signal.
        requery = 5.0 if locally_created else 0.5
        next_query = time.monotonic() + (requery if locally_created else 0.0)
        while time.monotonic() < deadline:
            with self._lock:
                state = self._actor_states.get(key)
            # Anti-entropy re-query: for UNKNOWN actors and for cached
            # NON-TERMINAL states alike. A cached "RESTARTING" pushed by
            # a GCS that then died would otherwise gate the query off
            # forever — its ALIVE transition was published while this
            # process's subscription was down, and no later push corrects
            # the cache (observed: 120s stalls after GCS failover).
            stale = state is not None and \
                state.get("state") not in ("ALIVE", "DEAD")
            if (state is None or stale) and time.monotonic() >= next_query:
                next_query = time.monotonic() + requery
                info = self.gcs.call("get_actor_info", {"actor_id": actor_id})
                if info["known"]:
                    state = {"state": info["state"], "address": info["address"],
                             "reason": info.get("death_cause"),
                             "error_blob": None}
                    if info["state"] in ("ALIVE", "DEAD"):
                        with self._lock:
                            self._actor_states[key] = state
            if state is not None:
                with self._lock:
                    self._created_pending.discard(key)
                if state["state"] == "ALIVE" and state.get("address"):
                    return state["address"]
                if state["state"] == "DEAD":
                    blob = state.get("error_blob")
                    if blob:
                        err = serialization.deserialize_exception(blob)
                        if isinstance(err, RayTaskError):
                            raise err.as_instanceof_cause()
                        raise err
                    raise ActorDiedError(actor_id, f"Actor {actor_id.hex()[:12]} is dead: "
                                                   f"{state.get('reason')}")
            ev = self._actor_events[key]
            ev.wait(timeout=0.5)
            ev.clear()
        raise GetTimeoutError(f"Timed out waiting for actor {actor_id.hex()[:12]}")

    def actor_liveness(self, actor_id: ActorID) -> str:
        """Non-blocking actor state probe: "alive" | "pending" | "dead".

        Pushed-state cache first, one bounded GCS directory query as
        fallback — never submits a task and never waits on creation.
        Health/ping loops use this BEFORE submitting to an actor: a
        submission to a not-yet-ALIVE actor resolves its address through
        a blocking wait_for_actor, so one wedged __init__ would park the
        prober (observed: the serve reconcile loop hostage to a replica
        stuck in its constructor — the stuck-state enforcement it owns
        could then never run)."""
        key = actor_id.binary()
        with self._lock:
            state = self._actor_states.get(key)
        st = state.get("state") if state is not None else None
        if st not in ("ALIVE", "DEAD"):
            # Unknown OR cached non-terminal: query the directory. A
            # cached RESTARTING must not be trusted forever — its ALIVE
            # transition may have been published while this process's
            # subscription was down (GCS failover), and treating it as
            # eternally "pending" would make health checks kill a
            # healthy replica (same staleness mode _wait_for_actor's
            # anti-entropy re-query covers).
            try:
                resp = self.gcs.call("get_actor_info",
                                     {"actor_id": actor_id}, timeout=5)
            except Exception:  # noqa: BLE001 — GCS mid-failover
                return "pending"
            if not resp.get("known"):
                return "pending"
            st = resp.get("state")
        if st == "ALIVE":
            return "alive"
        if st == "DEAD":
            return "dead"
        return "pending"

    def _actor_client(self, actor_id: ActorID) -> ActorClient:
        key = actor_id.binary()
        with self._lock:
            client = self._actor_clients.get(key)
            if client is not None and not client.client.is_closed:
                return client
        address = self.wait_for_actor(actor_id)
        with self._lock:
            client = self._actor_clients.get(key)
            if client is None or client.client.is_closed:
                client = ActorClient(self, actor_id, address)
                self._actor_clients[key] = client
            return client

    def submit_actor_task(self, spec: TaskSpec, retry_on_restart: int = 1
                          ) -> List[ObjectID]:
        if spec.trace_ctx is None:
            spec.trace_ctx = self.child_trace_ctx()
        rec = _TaskRecord(spec=spec)
        with self._lock:
            self._tasks[spec.task_id.binary()] = rec
            for oid in spec.return_ids():
                self._object_to_task[oid.binary()] = spec.task_id.binary()
        self._pin_deps(spec)
        self._submit_actor_attempt(spec, rec, retry_on_restart + 1)
        return spec.return_ids()

    def _submit_actor_attempt(self, spec: TaskSpec, rec: _TaskRecord,
                              attempts_left: int, last_err=None):
        """One pipelined send attempt; transport failures retry on the
        background executor (the restarted actor publishes a new address),
        terminal failures resolve the record to the death error."""
        if rec.event.is_set():
            return  # already resolved (e.g. actor-death path failed it)
        if attempts_left <= 0:
            self._unpin_deps(spec)
            self._fail_task_record(rec, spec, serialization.serialize_exception(
                ActorDiedError(spec.actor_id,
                               f"actor call failed: {last_err}")))
            return

        def retry(err):
            with self._lock:
                self._actor_clients.pop(spec.actor_id.binary(), None)
                self._actor_states.pop(spec.actor_id.binary(), None)
            time.sleep(0.1)
            self._submit_actor_attempt(spec, rec, attempts_left - 1, err)

        def cb(env, payload):
            if env.get("_lost") or env.get("e"):
                # Off the reader thread: the retry re-resolves the actor
                # address (blocking) and may sleep.
                self._bg_submit(retry, env.get("e") or "connection lost")

        try:
            client = self._actor_client(spec.actor_id)
            with client.lock:
                spec.seq_no = client.seq
                client.seq += 1
                client.client.call_async("actor_call", {"spec": spec}, cb)
        except (ConnectionLost, TimeoutError, RaySystemError) as e:
            retry(e)
        except Exception as e:  # noqa: BLE001 — actor terminally DEAD
            # (or its creation failed). Submitting to a dead actor must
            # not raise at the call site: the reference returns refs
            # that resolve to the death error on get.
            self._unpin_deps(spec)
            self._fail_task_record(
                rec, spec, serialization.serialize_exception(e))

    def _on_actor_conn_lost(self, actor_id: ActorID):
        """Direct connection to the actor's worker dropped: fail every
        in-flight task on that actor (the reference resolves them to
        RayActorError; restarted actors require fresh submissions unless
        max_task_retries is set)."""
        key = actor_id.binary()
        with self._lock:
            self._actor_clients.pop(key, None)
            # Force re-resolution of the address on the next call.
            state = self._actor_states.get(key)
            if state is not None and state.get("state") == "ALIVE":
                self._actor_states.pop(key, None)
            pending = [rec for rec in self._tasks.values()
                       if rec.spec is not None and rec.spec.actor_id == actor_id
                       and not rec.event.is_set()]
        err = serialization.serialize_exception(
            ActorDiedError(actor_id,
                           f"The actor {actor_id.hex()[:12]} died while this "
                           "task was in flight."))
        for rec in pending:
            self._unpin_deps(rec.spec)
            self._fail_task_record(rec, rec.spec, err)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.gcs.call("kill_actor", {"actor_id": actor_id, "no_restart": no_restart})

    def get_named_actor(self, name: str, namespace: Optional[str] = None):
        resp = self.gcs.call("get_named_actor",
                             {"name": name, "namespace": namespace or self.namespace})
        if not resp["found"]:
            raise ValueError(f"Failed to look up actor '{name}'. "
                             "It was either not created or died.")
        return resp["actor_id"], resp["creation_spec"]

    # ---------------------------------------------------------- job-scoped KV

    def _kv_namespace(self, namespace: Optional[str]) -> str:
        """GCS KV keys written through the public kv_* API live under a
        `job:<hex>:<ns>` namespace: the GCS purges the whole prefix when
        the job finishes (_finish_job), so no job can leak KV state or
        read/clobber another job's keys by accident. Detached actors
        wanting to outlive their job must use named actors or storage,
        never the owning job's KV."""
        return f"job:{self.job_id.hex()}:{namespace or 'default'}"

    def kv_put(self, key: str, value: bytes,
               namespace: Optional[str] = None) -> None:
        self.gcs.call("kv_put", {"namespace": self._kv_namespace(namespace),
                                 "key": key.encode(), "value": bytes(value)})

    def kv_get(self, key: str,
               namespace: Optional[str] = None) -> Optional[bytes]:
        resp = self.gcs.call("kv_get",
                             {"namespace": self._kv_namespace(namespace),
                              "key": key.encode()})
        return resp.get("value")

    def kv_del(self, key: str, namespace: Optional[str] = None) -> None:
        self.gcs.call("kv_del", {"namespace": self._kv_namespace(namespace),
                                 "key": key.encode()})

    # ----------------------------------------------------------------- get

    def get(self, object_ids: List[ObjectID], timeout: Optional[float] = None) -> List[Any]:
        deadline = time.monotonic() + timeout if timeout is not None else None
        state = {"blocked": False}

        def on_block():
            if not state["blocked"] and self.executing_task is not None:
                state["blocked"] = True
                self._notify_blocked(True)

        try:
            with _ParkedOp(f"get[{len(object_ids)}]"
                           + (f" {object_ids[0].hex()[:12]}" if object_ids
                              else "")):
                return [self._get_one(oid, deadline, on_block)
                        for oid in object_ids]
        finally:
            if state["blocked"]:
                self._notify_blocked(False)

    def _notify_blocked(self, blocked: bool):
        if self.executing_task is None:
            return
        try:
            self.raylet.call("worker_blocked" if blocked else "worker_unblocked", {},
                             timeout=5)
        except Exception:  # noqa: BLE001 — CPU-oversubscription hint only
            logger.debug("worker_(un)blocked notify failed", exc_info=True)

    @staticmethod
    def _maybe_raise(value: Any) -> Any:
        if isinstance(value, RayTaskError):
            raise value.as_instanceof_cause()
        return value

    def _get_one(self, oid: ObjectID, deadline: Optional[float], on_block=None) -> Any:
        key = oid.binary()
        while True:
            cached = self._object_cache.get(key, _PENDING)
            if cached is not _PENDING:
                return self._maybe_raise(cached)
            task_key = self._object_to_task.get(key)
            rec = self._tasks.get(task_key) if task_key is not None else None
            if rec is not None:
                if not rec.event.is_set():
                    if on_block:
                        on_block()
                    remaining = None if deadline is None else deadline - time.monotonic()
                    if remaining is not None and remaining <= 0:
                        raise GetTimeoutError("Get timed out")
                    if not rec.event.wait(remaining):
                        raise GetTimeoutError("Get timed out")
                if rec.error is not None:
                    err = serialization.deserialize_exception(rec.error)
                    if isinstance(err, RayTaskError):
                        raise err.as_instanceof_cause()
                    raise err
                cached = self._object_cache.get(key, _PENDING)
                if cached is not _PENDING:
                    return self._maybe_raise(cached)
                # Large result: fall through to store fetch.
            value = self.store.get_value(oid) if self.store.contains(oid) else _PENDING
            if value is not _PENDING:
                self._object_cache[key] = value
                return self._maybe_raise(value)
            if on_block:
                on_block()
            status, data = self._fetch_via_raylet(oid, deadline)
            if status == "local":
                value = self.store.get_value(oid)
            elif status == "inline":
                value = serialization.deserialize(data)
            elif status == "lost" and self._try_reconstruct(oid):
                # Creating task resubmitted: loop back and wait on it.
                continue
            else:
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(f"Timed out getting {oid}")
                raise ObjectLostError(oid)
            self._object_cache[key] = value
            return self._maybe_raise(value)

    def _fetch_via_raylet(self, oid: ObjectID, deadline: Optional[float]
                          ) -> Tuple[str, Any]:
        """Make the object available via the local raylet, event-driven.

        get_or_pull answers local/inline immediately or registers this
        process as a waiter and returns "pending"; the raylet then pushes
        object_ready / object_unavailable (no 5 ms poll loops on either
        side — reference pull manager behavior, `pull_manager.h:52`).
        Returns (status, inline_data|None); status in
        {local, inline, lost, error, timeout}.
        """
        key = oid.binary()
        with self._lock:
            entry = self._object_events.get(key)
            if entry is None:
                entry = self._object_events[key] = [threading.Event(), 0]
            entry[1] += 1
        ev = entry[0]
        status = "timeout"
        try:
            while True:
                ev.clear()
                resp = self.raylet.call("get_or_pull", {"object_id": oid},
                                        timeout=30)
                status = resp["status"]
                if status in ("local", "inline"):
                    return status, resp.get("data")
                if status == "error":
                    # Non-retryable local failure (e.g. object larger than
                    # the node store) — raise, don't loop.
                    raise RaySystemError(
                        f"cannot materialize {oid}: {resp.get('error')}")
                # "pending": a known entry with zero copies means every
                # holder died — the owner should reconstruct, not wait.
                if resp.get("known") and not resp.get("has_copies"):
                    return "lost", None
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return "timeout", None
                # Wake instantly on the raylet's push; the 1 s cap is a
                # safety net for transitions with no push (e.g. the holding
                # node died while we waited).
                wait_t = 1.0 if remaining is None else min(1.0, remaining)
                ev.wait(wait_t)
        finally:
            with self._lock:
                entry[1] -= 1
                if entry[1] <= 0:
                    self._object_events.pop(key, None)
            if status in ("timeout", "lost"):
                # Deregister from the raylet so it stops pulling for nobody.
                try:
                    self.raylet.call("cancel_object_wait",
                                     {"object_id": oid}, timeout=5)
                except Exception:  # noqa: BLE001
                    pass

    def local_result_size(self, oid: ObjectID) -> Optional[int]:
        """Sealed byte size of a task-output object we own, read from the
        completion record the worker already pushed — no directory round
        trip. None when unknown (inline result, put, not ours)."""
        key = oid.binary()
        with self._lock:
            task_key = self._object_to_task.get(key)
            rec = self._tasks.get(task_key) if task_key is not None else None
            if rec is None or not rec.results:
                return None
            for r in rec.results:
                roid = r.get("object_id")
                if roid is not None and roid.binary() == key:
                    size = r.get("size")
                    return int(size) if size else None
        return None

    def reexecute_task_for(self, oid: ObjectID) -> bool:
        """Re-run the task that created `oid` (owner-side), even when the
        task 'completed' — with a loss-shaped ERROR result because a
        dependency died under it (the raylet fails parked tasks on lost
        deps instead of hanging them). Callers must have seen loss-shaped
        evidence for the object; bounded by the same per-task budget as
        reconstruction. Returns True when a re-execution is in flight."""
        return self._try_reconstruct(oid)

    def _try_reconstruct(self, oid: ObjectID, depth: int = 0) -> bool:
        """Owner-side lineage reconstruction: re-execute the creating task
        when every copy of one of its returns is gone (reference
        `object_recovery_manager.h:106`; bounded like `task_manager.h:97`).

        Only the owner holds the spec, so only the owner can recover; puts
        and actor-task results are not replayable. Missing dependencies are
        rebuilt first, bottom-up, capped by depth and per-task attempt
        budget. Returns True if a re-execution is (already) in flight.
        """
        if depth > GLOBAL_CONFIG.max_reconstruction_depth:
            return False
        key = oid.binary()
        with self._lock:
            task_key = self._object_to_task.get(key)
            rec = self._tasks.get(task_key) if task_key is not None else None
            if rec is None or rec.spec is None:
                return False  # not ours, or a put: unrecoverable
            spec = rec.spec
            if spec.actor_id is not None or spec.actor_creation:
                return False  # actor state is not replayable
            if not rec.event.is_set():
                return True  # concurrent getter already resubmitted
            if rec.reconstructions >= GLOBAL_CONFIG.max_object_reconstructions:
                return False
            rec.reconstructions += 1
            self.reconstructions_total += 1
            rec.event.clear()
            rec.results = None
            rec.error = None
            for r in spec.return_ids():
                self._object_cache.pop(r.binary(), None)
        logger.warning("object %s lost: re-executing task %s (attempt %d)",
                       oid.hex()[:12], spec.name, rec.reconstructions)
        for dep in spec.dependencies():
            if not self._dep_alive(dep) and not self._try_reconstruct(dep, depth + 1):
                self._fail_task_record(rec, spec, serialization.serialize_exception(
                    ObjectLostError(dep)))
                return True  # the error record is the answer
        self._pin_deps(spec)
        try:
            self._submit_spec(spec)
        except Exception as e:  # noqa: BLE001
            self._unpin_deps(spec)
            self._fail_task_record(rec, spec, serialization.serialize_exception(
                RaySystemError(f"reconstruction submit failed: {e}")))
        return True

    def _fail_task_record(self, rec: _TaskRecord, spec: TaskSpec, blob: bytes):
        """Record a terminal error AND materialize it as the task's return
        objects in the directory, so tasks elsewhere that depend on them
        get scheduled and re-raise instead of waiting forever (same
        contract as the normal completion path in _on_raylet_push)."""
        with self._lock:
            rec.error = blob
            rec.event.set()
        for oid in spec.return_ids():
            try:
                self.gcs.call("object_location_add",
                              {"object_id": oid, "inline": blob,
                               "size": len(blob)}, timeout=10)
            except Exception:  # noqa: BLE001
                pass
        self._notify_waiters(spec.task_id.binary())

    def _notify_waiters(self, task_key: Optional[bytes]):
        """Wake active wait() calls with the completed task's key (None:
        non-task object progress — waiters rescan their store/GCS-backed
        refs)."""
        with self._lock:
            watchers = list(self._wait_watchers)
            resolvers = (self._future_waiters.pop(task_key, ())
                         if task_key is not None else ())
        for dq, ev in watchers:
            dq.append(task_key)
            ev.set()
        for resolve in resolvers:
            self._resolver_pool().submit(resolve)

    def _resolver_pool(self):
        if self._future_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._lock:
                if self._future_pool is None:
                    self._future_pool = ThreadPoolExecutor(
                        2, thread_name_prefix="ref-future")
        return self._future_pool

    def get_future(self, oid: ObjectID):
        """concurrent.futures.Future resolving to the object's value.

        Async servers (`asyncio.wrap_future`) await completions without a
        blocked thread per request: the future's resolve (a local fetch +
        deserialize — the object is ready by then) runs on a small shared
        pool fed by task-completion events. Refs with no local task record
        fall back to a pooled blocking get.
        """
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def resolve():
            if not fut.set_running_or_notify_cancel():
                return
            try:
                fut.set_result(self._get_one(oid, None))
            except BaseException as e:  # noqa: BLE001 — delivered to awaiter
                fut.set_exception(e)

        task_key = self._object_to_task.get(oid.binary())
        rec = self._tasks.get(task_key) if task_key is not None else None
        if rec is None or rec.event.is_set():
            self._resolver_pool().submit(resolve)
            return fut
        with self._lock:
            self._future_waiters.setdefault(task_key, []).append(resolve)
        if rec.event.is_set():
            # Completion landed between the check and the registration;
            # the notifier may have already drained — drain idempotently.
            with self._lock:
                resolvers = self._future_waiters.pop(task_key, ())
            for r in resolvers:
                self._resolver_pool().submit(r)
        return fut

    def cancel(self, oid: ObjectID, force: bool = False):
        """Cancel the task producing `oid` (reference ray.cancel): queued
        tasks are dropped, running tasks interrupted (force kills the
        worker). No-op for unknown/finished tasks; actor tasks refuse."""
        rec = self._tasks.get(self._object_to_task.get(oid.binary(), b""))
        if rec is None or rec.spec is None:
            return
        if rec.spec.actor_id is not None:
            # Actor tasks: queued calls drop; running async calls get
            # CancelledError at the next await; running sync calls are
            # uninterruptible (reference actor-cancel semantics —
            # force-kill would destroy actor state).
            if force:
                raise ValueError(
                    "force=True cannot cancel actor tasks (it would kill "
                    "the actor); use ray_tpu.kill for that")
            try:
                client = self._actor_client(rec.spec.actor_id)
                client.client.call_async("cancel_actor_task",
                                         {"task_id": rec.spec.task_id})
            except Exception:  # noqa: BLE001 — actor dead: ref resolves
                pass           # to ActorDiedError anyway
            return
        if rec.spec.direct and self._direct.cancel(rec.spec.task_id, force):
            return
        addr = rec.submitted_addr
        client = self.raylet if addr in (None, self.raylet.address) \
            else self._raylet_for(addr)
        client.call("cancel_task",
                    {"task_id": rec.spec.task_id, "force": force},
                    timeout=30)

    def _dep_alive(self, oid: ObjectID) -> bool:
        """Cluster-visible existence: inline in the directory or at least
        one live node holds a copy."""
        try:
            e = self.gcs.call("object_locations_get", {"object_id": oid},
                              timeout=5)
        except Exception:  # noqa: BLE001
            return False
        return bool(e.get("known")
                    and (e.get("inline") is not None or e.get("nodes")))

    # ---------------------------------------------------------------- wait

    def wait(self, object_ids: List[ObjectID], num_returns: int = 1,
             timeout: Optional[float] = None) -> Tuple[List[ObjectID], List[ObjectID]]:
        from collections import deque as _deque

        deadline = None if timeout is None else time.monotonic() + timeout
        # Register the watcher BEFORE the initial scan so a completion
        # landing mid-scan is never missed (it lands in the deque and is
        # drained on the first wake).
        notif = (_deque(), threading.Event())
        dq, ev = notif
        with self._lock:
            self._wait_watchers.append(notif)
        ready_keys: set = set()
        n_ready = 0
        parked = _ParkedOp(f"wait[{len(object_ids)}/{num_returns}]")
        try:
            # One full scan, then purely event-driven: completed task keys
            # map back to their pending refs, so each completion costs O(1)
            # instead of a rescan of every pending ref.
            by_task: Dict[bytes, List[ObjectID]] = {}
            others: List[ObjectID] = []
            for oid in object_ids:
                if self._is_ready(oid):
                    ready_keys.add(oid.binary())
                    n_ready += 1
                    continue
                tk = self._object_to_task.get(oid.binary())
                if tk is not None:
                    by_task.setdefault(tk, []).append(oid)
                else:
                    others.append(oid)
            last_others_scan = time.monotonic()
            while n_ready < num_returns and (by_task or others):
                if deadline is not None and time.monotonic() >= deadline:
                    break
                wait_t = 0.1 if deadline is None \
                    else min(0.1, max(0.0, deadline - time.monotonic()))
                ev.wait(wait_t)
                ev.clear()
                rescan_others = False
                while dq:
                    tk = dq.popleft()
                    if tk is None:
                        rescan_others = True
                        continue
                    for oid in by_task.pop(tk, ()):
                        if self._is_ready(oid):
                            ready_keys.add(oid.binary())
                            n_ready += 1
                        else:  # record pruned mid-wait: fall back to polling
                            others.append(oid)
                # Store/GCS-backed refs (no local task record) have no push
                # channel here: poll at 100 ms, same as the old scan cadence.
                if others and (rescan_others or
                               time.monotonic() - last_others_scan >= 0.1):
                    last_others_scan = time.monotonic()
                    still = []
                    for oid in others:
                        if self._is_ready(oid):
                            ready_keys.add(oid.binary())
                            n_ready += 1
                        else:
                            still.append(oid)
                    others = still
        finally:
            parked.__exit__()
            with self._lock:
                try:
                    self._wait_watchers.remove(notif)
                except ValueError:
                    pass
        # Preserve input order; cap ready at num_returns (overflow stays
        # in the pending list, matching the reference wait() contract).
        ordered_ready = [o for o in object_ids if o.binary() in ready_keys]
        capped = ordered_ready[:num_returns]
        capped_set = {o.binary() for o in capped}
        return capped, [o for o in object_ids if o.binary() not in capped_set]

    def _is_ready(self, oid: ObjectID) -> bool:
        key = oid.binary()
        if key in self._object_cache:
            return True
        task_key = self._object_to_task.get(key)
        if task_key is not None:
            rec = self._tasks.get(task_key)
            if rec is not None:
                return rec.event.is_set()
        if self.store.contains(oid):
            return True
        try:
            entry = self.gcs.call("object_locations_get", {"object_id": oid}, timeout=5)
            return bool(entry.get("known") and
                        (entry.get("inline") is not None or entry.get("nodes")))
        except Exception:  # noqa: BLE001 — unreachable GCS == not available
            logger.debug("object_locations_get for %s failed", oid,
                         exc_info=True)
            return False

    # ------------------------------------------------------------- cleanup

    def register_ref(self, oid: ObjectID):
        with self._lock:
            self._ref_counts[oid.binary()] += 1

    def is_owner(self, oid: ObjectID) -> bool:
        key = oid.binary()
        return key in self._owned_puts or key in self._object_to_task

    def on_refs_deserialized(self, oids: List[ObjectID]):
        """This process deserialized refs it does not own: register as a
        borrower with the directory, SYNCHRONOUSLY and in one batch — the
        owner's submit-time pin (nested_refs) holds only until the task
        completes, so the borrows must be on record before user code
        runs."""
        if self._closed:
            return
        fresh: List[ObjectID] = []
        with self._lock:
            for oid in oids:
                key = oid.binary()
                if self.is_owner(oid) or key in self._borrowed:
                    continue
                self._borrowed.add(key)
                fresh.append(oid)
        if not fresh:
            return
        try:
            self.gcs.call("borrow_add",
                          {"object_ids": fresh,
                           "borrower_id": self.worker_id.hex()}, timeout=10)
        except Exception:  # noqa: BLE001 — GCS hiccup: refs still usable,
            pass           # at worst the objects outlive this borrower

    @staticmethod
    def _lineage_bytes(spec: TaskSpec) -> int:
        """Rough retained-lineage cost of one spec: inline arg payloads
        plus a per-record overhead charge (spec + record objects are a
        few KiB of real memory even with pure-ref args — the base keeps
        the retained-record COUNT honest, not just the blob bytes)."""
        try:
            return 4096 + sum(
                len(p) for _k, p in spec.args
                if isinstance(p, (bytes, bytearray, memoryview)))
        except Exception:  # noqa: BLE001 — cost estimate only
            return 8192

    def _retire_lineage(self, task_key: bytes, rec: _TaskRecord):
        """Last reference to a completed task's outputs dropped: keep the
        record re-executable (lineage) in a byte-bounded retirement
        queue instead of dropping it. Eviction (oldest first, skipping
        records that went back in flight or in scope) drops the record
        AND its object->task mappings — past the bound, a lost object is
        unrecoverable, exactly the `lineage_max_bytes` contract. Caller
        holds self._lock."""
        if task_key in self._retired_lineage:
            return
        cost = self._lineage_bytes(rec.spec)
        self._retired_lineage[task_key] = cost
        self._retired_lineage_bytes += cost
        cap = max(0, GLOBAL_CONFIG.lineage_max_bytes)
        for _ in range(len(self._retired_lineage)):
            if self._retired_lineage_bytes <= cap:
                break
            old_key, old_cost = self._retired_lineage.popitem(last=False)
            old_rec = self._tasks.get(old_key)
            busy = old_rec is not None and (
                not old_rec.event.is_set()
                or any(self._ref_counts.get(r.binary(), 0) > 0
                       for r in (old_rec.spec.return_ids()
                                 if old_rec.spec is not None else [])))
            if busy:  # re-executing or back in scope: keep, re-queue
                self._retired_lineage[old_key] = old_cost
                continue
            self._retired_lineage_bytes -= old_cost
            self._drop_lineage(old_key, old_rec)

    def _drop_lineage(self, task_key: bytes, rec: Optional[_TaskRecord]):
        self._tasks.pop(task_key, None)
        if rec is not None and rec.spec is not None:
            for r in rec.spec.return_ids():
                if self._object_to_task.get(r.binary()) == task_key:
                    self._object_to_task.pop(r.binary(), None)

    def deregister_ref(self, oid: ObjectID):
        if self._closed:
            return
        key = oid.binary()
        with self._lock:
            self._ref_counts[key] -= 1
            if self._ref_counts[key] > 0:
                return
            self._ref_counts.pop(key, None)
            # Prune driver-side caches so long-running drivers don't leak
            # one record per completed task (see reference TaskManager's
            # completed-task eviction).
            self._object_cache.pop(key, None)
            if key in self._borrowed:
                # Borrowers never free: they only remove themselves from
                # the borrower set (the owner's pending-free fires when
                # the set empties).
                self._borrowed.discard(key)
                borrow = True
            else:
                borrow = False
                owned = key in self._owned_puts or key in self._object_to_task
                self._owned_puts.discard(key)
                # LINEAGE RETENTION: keep the record (and the
                # object->task mapping) so the creating task stays
                # re-executable after the object is freed — a downstream
                # task may still need this block rebuilt when a node
                # dies (Exoshuffle's contract: shuffle intermediates are
                # recomputable from retained lineage, not re-read from a
                # bespoke service). The retirement queue bounds retained
                # lineage by `lineage_max_bytes`.
                task_key = self._object_to_task.get(key)
                if task_key is not None:
                    rec = self._tasks.get(task_key)
                    replayable = (rec is not None and rec.spec is not None
                                  and rec.spec.actor_id is None
                                  and not rec.spec.actor_creation)
                    if not replayable:
                        # Pre-retention behavior for records lineage can
                        # never replay (actor results, dangling maps).
                        self._object_to_task.pop(key, None)
                        if rec is not None and rec.event.is_set():
                            returns = rec.spec.return_ids() \
                                if rec.spec is not None else []
                            if not any(r.binary() in self._object_to_task
                                       for r in returns):
                                self._tasks.pop(task_key, None)
                    elif rec.event.is_set():
                        returns = rec.spec.return_ids()
                        if not any(self._ref_counts.get(r.binary(), 0) > 0
                                   for r in returns):
                            self._retire_lineage(task_key, rec)
                if not owned:
                    # Not ours and not registered as a borrow (e.g. created
                    # before tracking): never free somebody else's object.
                    return
                if self._dep_pins.get(key, 0) > 0:
                    self._deferred_free.add(key)
                    return
        if borrow:
            try:
                self.gcs.call_async("borrow_remove",
                                    {"object_id": oid,
                                     "borrower_id": self.worker_id.hex()})
            except Exception:  # noqa: BLE001
                pass
            return
        self.free_ref(oid)

    def _pin_deps(self, spec: TaskSpec):
        with self._lock:
            for dep in spec.dependencies() + list(spec.nested_refs):
                self._dep_pins[dep.binary()] += 1

    def _unpin_deps(self, spec: TaskSpec):
        to_free = []
        with self._lock:
            for dep in spec.dependencies() + list(spec.nested_refs):
                key = dep.binary()
                self._dep_pins[key] -= 1
                if self._dep_pins[key] <= 0:
                    self._dep_pins.pop(key, None)
                    if key in self._deferred_free:
                        self._deferred_free.discard(key)
                        to_free.append(dep)
        for dep in to_free:
            self.free_ref(dep)

    def free_ref(self, oid: ObjectID):
        """Owner dropped its last reference; batch-free in the directory.

        Flushes at 100 ids or after 1s (timer), so drivers freeing fewer
        than 100 objects still release GCS directory entries promptly.
        """
        if self._closed:
            return
        with self._lock:
            self._free_buffer.append(oid)
            # Pool-tracked puts flush now: their segments only become
            # reusable once the directory confirms the free, and a warm
            # segment idling in the batch buffer is a wasted recycle.
            flush = (len(self._free_buffer) >= 100
                     or self._segment_pool.is_tracked(oid))
            if not flush and self._free_timer is None:
                self._free_timer = threading.Timer(1.0, self._flush_free_buffer)
                self._free_timer.daemon = True
                self._free_timer.start()
        if flush:
            self._flush_free_buffer()

    def _flush_free_buffer(self):
        with self._lock:
            if self._free_timer is not None:
                self._free_timer.cancel()
                self._free_timer = None
            if not self._free_buffer:
                return
            batch, self._free_buffer = self._free_buffer, []
        pool = self._segment_pool
        msg: Dict[str, Any] = {"object_ids": batch}
        tracked = [o for o in batch if pool.is_tracked(o)]
        if tracked:
            msg["defer_unlink"] = tracked
            msg["defer_node"] = self.node_id
        try:
            resp = self.gcs.call("free_objects", msg, timeout=5)
        except Exception:  # noqa: BLE001 — fall back to direct unlink
            logger.debug("free_objects RPC failed; forgetting %d tracked "
                         "segments", len(tracked), exc_info=True)
            for oid in tracked:
                pool.forget(oid)
            return
        if not tracked:
            return
        freed = {o.binary() for o in (resp or {}).get("freed", ())}
        for oid in tracked:
            if oid.binary() in freed:
                ok = pool.reclaim(
                    oid,
                    can_reuse=lambda o=oid: self.store.release_if_unused(o))
                if not ok:
                    # The raylet skipped the unlink on our behalf; if the
                    # segment didn't make it into the pool (exports still
                    # live, pool full), remove the orphaned file now.
                    try:
                        os.unlink("/dev/shm/" + _segment_name(
                            self.session_suffix, oid))
                    except OSError:
                        pass
            else:
                # Deferred (still borrowed): the eventual free unlinks it
                # on the raylet as usual; nothing to recycle.
                pool.forget(oid)

    def shutdown(self):
        self._flush_free_buffer()
        self._segment_pool.close()
        if self._future_pool is not None:
            self._future_pool.shutdown(wait=False)
        if self._borrowed:
            # Graceful exit drops every borrow in one call so pending
            # frees fire now instead of leaking until worker-death cleanup.
            try:
                self.gcs.call("borrower_gone",
                              {"borrower_id": self.worker_id.hex()},
                              timeout=5)
            except Exception:  # noqa: BLE001
                pass
        try:
            self._metrics_pusher.stop()
        except Exception:  # noqa: BLE001
            pass
        self._closed = True
        try:
            self._direct.shutdown()
        except Exception:  # noqa: BLE001
            pass
        if self._bg_executor is not None:
            self._bg_executor.shutdown(wait=False)
        for c in self._actor_clients.values():
            c.client.close()
        for c in self._raylet_clients.values():
            c.close()
        self.gcs.close()
        self.store.close()
