"""Runtime environments: working_dir / py_modules / env_vars per task.

Equivalent of the reference's runtime-env system
(`python/ray/_private/runtime_env/{working_dir,py_modules,packaging}.py`,
design doc `python/ray/runtime_env/ARCHITECTURE.md`), collapsed to the
framework's needs:

- **Packaging** (driver side): a local directory zips into a
  content-addressed blob stored once in the GCS KV
  (`kv://runtime_env/<sha>.zip`); the task spec carries only URIs.
- **Isolation** (raylet side): URIs become part of the worker's granted
  env (`RAY_TPU_RUNTIME_ENV`), so the worker pool leases tasks only to
  workers built with the same environment — two tasks with different
  working_dirs never share a process.
- **Materialization** (worker side): at startup the worker fetches blobs
  it hasn't cached under `session_dir/runtime_env/<sha>/`, extracts,
  chdirs into the working_dir and prepends py_modules to sys.path.

- **pip / venv** (worker side): `{"pip": [...], "pip_wheelhouse": dir}`
  builds a venv from a LOCAL wheelhouse (`pip install --no-index
  --find-links`), offline by design — the target hosts have no package
  index. Venvs are cached per content hash (package list + wheelhouse
  manifest) under the session dir and activated by prepending their
  site-packages to sys.path; the pip spec rides the same
  `RAY_TPU_RUNTIME_ENV` marker, so env-matched worker leasing keeps
  different pip environments in different processes. (The reference's
  pip plugin, `python/ray/_private/runtime_env/pip.py`, re-launches
  workers inside the venv and resolves from an index; both are
  unavailable/unwanted here.)

conda/container isolation is out of scope; `env_vars` pass through.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

URI_PREFIX = "kv://runtime_env/"
_KV_NS = "runtime_env"
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 256 * 1024 * 1024


def _zip_dir(path: str, prefix: str = "") -> bytes:
    """Zip a directory; `prefix` nests entries under `<prefix>/...` (used
    by py_modules so extraction recreates the importable package dir)."""
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for f in files:
                full = os.path.join(root, f)
                rel = os.path.relpath(full, base)
                zf.write(full, os.path.join(prefix, rel) if prefix else rel)
    blob = buf.getvalue()
    if len(blob) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} is {len(blob)} bytes "
            f"(max {MAX_PACKAGE_BYTES}); exclude large data directories")
    return blob


def _upload(gcs, blob: bytes) -> str:
    sha = hashlib.sha256(blob).hexdigest()[:32]
    uri = f"{URI_PREFIX}{sha}.zip"
    key = uri.encode()
    exists = gcs.call("kv_exists", {"namespace": _KV_NS, "key": key})
    if not exists.get("exists"):
        gcs.call("kv_put", {"namespace": _KV_NS, "key": key, "value": blob})
    return uri


def _normalize_pip(out: Dict[str, Any]) -> None:
    """Canonicalize the pip spec: {"pip": [...pkgs...]} (+ optional
    "pip_wheelhouse") or {"pip": {"packages": [...], "wheelhouse": ...}}
    into the dict form with an absolute wheelhouse path. Validated driver
    side so a typo'd wheelhouse fails at submission, not in a worker."""
    pip = out.get("pip")
    if pip is None:
        return
    if isinstance(pip, dict):
        packages = list(pip.get("packages") or [])
        wheelhouse = pip.get("wheelhouse") or out.pop("pip_wheelhouse", None)
    else:
        packages = list(pip)
        wheelhouse = out.pop("pip_wheelhouse", None)
    wheelhouse = wheelhouse or os.environ.get("RAY_TPU_WHEELHOUSE")
    if not packages:
        out.pop("pip", None)
        return
    if not wheelhouse:
        raise ValueError(
            "runtime_env pip requires a wheelhouse (pip_wheelhouse=..., "
            "pip={'wheelhouse': ...} or RAY_TPU_WHEELHOUSE): this "
            "environment installs offline from local wheels only")
    wheelhouse = os.path.abspath(wheelhouse)
    if not os.path.isdir(wheelhouse):
        raise ValueError(f"pip wheelhouse {wheelhouse!r} is not a directory")
    out["pip"] = {"packages": sorted(packages), "wheelhouse": wheelhouse}
    # Hash computed DRIVER-side and carried in the spec (hence in the
    # worker-pool env marker): rebuilding a wheel changes the marker, so
    # pooled workers on the stale venv are never re-leased for the new
    # env — they'd otherwise serve old code from their sys.path.
    out["pip"]["env_hash"] = pip_env_hash(out["pip"])


def env_hash(runtime_env: Optional[Dict[str, Any]]) -> str:
    """Stable short content hash identifying one runtime environment —
    THE `env_sig` that keys worker-lease compatibility end to end
    (direct_task lease keys, the raylet's granted-env marker, and the
    forge's per-env template selection all derive from this one value).
    Empty env -> "" so the no-runtime_env fast path stays marker-free.

    Canonicalization: list-valued keys sort (py_modules/preimports order
    must not fork worker pools); everything else goes through json with
    repr fallback, so an exotic value degrades to a stable string rather
    than raising mid-submission."""
    if not runtime_env:
        return ""
    canon: Dict[str, Any] = {}
    for k in sorted(runtime_env):
        v = runtime_env[k]
        if isinstance(v, (list, tuple, set)):
            canon[k] = sorted(str(x) for x in v)
        elif isinstance(v, dict):
            canon[k] = {str(kk): str(v[kk]) for kk in sorted(v)}
        else:
            canon[k] = v
    blob = json.dumps(canon, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _normalize_preimports(out: Dict[str, Any]) -> None:
    """Canonicalize {"preimports": [...module names...]}: the modules a
    job wants baked into its forge template so its workers fork warm.
    Validated at submission (a typo'd module name must fail the submit,
    not wedge a template on every node)."""
    pre = out.get("preimports")
    if pre is None:
        return
    mods = sorted({str(m).strip() for m in pre if str(m).strip()})
    for m in mods:
        if not all(seg.isidentifier() for seg in m.split(".")):
            raise ValueError(
                f"runtime_env preimports entry {m!r} is not a valid "
                "module path")
    if mods:
        out["preimports"] = mods
    else:
        out.pop("preimports", None)


def pip_env_hash(pip: Dict[str, Any]) -> str:
    """Content hash identifying one venv: the package list plus the
    wheelhouse manifest (path + file names + sizes + mtimes — mtime
    catches a rebuilt wheel whose byte size happens to match), so adding
    or rebuilding a wheel produces a fresh venv instead of stale-cache
    confusion."""
    h = hashlib.sha256()
    for p in pip["packages"]:
        h.update(p.encode())
        h.update(b"\0")
    wh = pip["wheelhouse"]
    h.update(wh.encode())
    try:
        for name in sorted(os.listdir(wh)):
            if name.endswith(".whl"):
                st = os.stat(os.path.join(wh, name))
                h.update(name.encode())
                h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
    except OSError:
        pass
    return h.hexdigest()[:24]


def prepare(runtime_env: Optional[Dict[str, Any]], gcs
            ) -> Optional[Dict[str, Any]]:
    """Driver side: replace local paths with uploaded content URIs.
    Idempotent (URIs pass through untouched)."""
    if not runtime_env:
        return runtime_env
    out = dict(runtime_env)
    _normalize_pip(out)
    _normalize_preimports(out)
    wd = out.get("working_dir")
    if wd and not wd.startswith(URI_PREFIX):
        if not os.path.isdir(wd):
            raise ValueError(f"runtime_env working_dir {wd!r} is not a "
                             "directory")
        out["working_dir"] = _upload(gcs, _zip_dir(wd))
    mods = out.get("py_modules")
    if mods:
        uris: List[str] = []
        for m in mods:
            if isinstance(m, str) and m.startswith(URI_PREFIX):
                uris.append(m)
            elif isinstance(m, str) and os.path.isdir(m):
                # The module DIRECTORY itself is the importable package:
                # nest it so extraction recreates `<name>/...` on sys.path.
                name = os.path.basename(os.path.normpath(m))
                uris.append(_upload(gcs, _zip_dir(m, prefix=name)))
            else:
                raise ValueError(
                    f"py_modules entry {m!r} must be a directory")
        out["py_modules"] = uris
    return out


class EnvCache:
    """Memoizing prepare() shared by the driver runtime and ray:// client.

    A loop submitting N tasks with one runtime_env zips the directory
    once; entries re-validate every `revalidate_s` against the KV (the
    blob store LRU-evicts under memory pressure — a vanished package
    re-uploads instead of failing every later worker launch)."""

    def __init__(self, gcs, revalidate_s: float = 60.0):
        import threading
        import time as _time

        self._gcs = gcs
        self._revalidate_s = revalidate_s
        self._lock = threading.Lock()
        self._time = _time
        self._entries: Dict[str, Any] = {}  # key -> (prepared, checked_ts)

    def prepare(self, runtime_env: Optional[Dict[str, Any]]
                ) -> Optional[Dict[str, Any]]:
        if not runtime_env or not (runtime_env.get("working_dir")
                                   or runtime_env.get("py_modules")
                                   or runtime_env.get("pip")):
            return runtime_env
        key = repr(sorted((k, repr(v)) for k, v in runtime_env.items()))
        now = self._time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and now - entry[1] < self._revalidate_s:
                return entry[0]
        prepared = entry[0] if entry is not None else None
        if prepared is None or not self._uris_exist(prepared) \
                or not self._pip_fresh(prepared):
            prepared = prepare(runtime_env, self._gcs)
        with self._lock:
            # Deliberate prepared-env cache: keys are distinct
            # runtime_env signatures (bounded by the workload's env
            # variety) and entries are revalidated, not per-request.
            # raylint: disable=RL011 — bounded by distinct runtime_envs
            self._entries[key] = (prepared, now)
        return prepared

    @staticmethod
    def _pip_fresh(prepared: Dict[str, Any]) -> bool:
        """Re-hash the wheelhouse at revalidation: a rebuilt wheel must
        produce a new env marker (and thus fresh workers/venvs) within
        one revalidate window."""
        pip = prepared.get("pip")
        if not pip or not isinstance(pip, dict):
            return True
        return pip.get("env_hash") == pip_env_hash(pip)

    def _uris_exist(self, prepared: Dict[str, Any]) -> bool:
        uris = [prepared.get("working_dir")] + list(
            prepared.get("py_modules") or [])
        for uri in uris:
            if uri and uri.startswith(URI_PREFIX):
                resp = self._gcs.call("kv_exists",
                                      {"namespace": _KV_NS,
                                       "key": uri.encode()})
                if not resp.get("exists"):
                    return False
        return True


def granted_env(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, str]:
    """Raylet side: the env-var marker that isolates worker pools per
    runtime environment (URIs + pip spec — env_vars are granted
    separately)."""
    if not runtime_env:
        return {}
    uris = {k: runtime_env[k]
            for k in ("working_dir", "py_modules", "pip", "preimports")
            if runtime_env.get(k)}
    if not uris:
        return {}
    # The env_sig rides next to the marker so every layer (worker-pool
    # leasing, per-env forge templates, job reclaim) keys off ONE hash
    # instead of re-deriving its own flavor of "same environment".
    return {"RAY_TPU_RUNTIME_ENV": json.dumps(uris, sort_keys=True),
            "RAY_TPU_ENV_SIG": env_hash(runtime_env)}


def materialize(gcs, session_dir: str) -> None:
    """Worker side: fetch + extract this process's runtime env (from the
    RAY_TPU_RUNTIME_ENV marker), chdir into the working_dir, prepend
    py_modules to sys.path. Runs once at worker startup."""
    marker = os.environ.get("RAY_TPU_RUNTIME_ENV")
    if not marker:
        return
    uris = json.loads(marker)
    cache = os.path.join(session_dir, "runtime_env")
    os.makedirs(cache, exist_ok=True)

    def fetch(uri: str) -> str:
        import shutil
        import tempfile

        sha = uri[len(URI_PREFIX):-len(".zip")]
        dest = os.path.join(cache, sha)
        if not os.path.isdir(dest):
            blob = gcs.call("kv_get", {"namespace": _KV_NS,
                                       "key": uri.encode()})["value"]
            if blob is None:
                raise RuntimeError(f"runtime_env blob {uri} missing from "
                                   "GCS KV")
            # Unique staging dir + tolerate losing the rename race:
            # several workers with the same env extract concurrently.
            tmp = tempfile.mkdtemp(prefix=f"{sha}.", dir=cache)
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.rename(tmp, dest)
            except OSError:
                if not os.path.isdir(dest):
                    raise
                shutil.rmtree(tmp, ignore_errors=True)  # lost the race
        return dest

    pip = uris.get("pip")
    if pip:
        _activate_venv(_ensure_venv(pip, cache))
    # Preimports: forge-templated workers already hold these modules from
    # the template process; this covers the cold-spawn fallback so both
    # paths present an identical environment to user code.
    import importlib
    for mod in uris.get("preimports", []) or []:
        try:
            importlib.import_module(mod)
        except Exception:
            logger.warning("runtime_env: preimport %s failed", mod,
                           exc_info=True)
    for uri in uris.get("py_modules", []) or []:
        path = fetch(uri)
        if path not in sys.path:
            sys.path.insert(0, path)
    wd = uris.get("working_dir")
    if wd:
        path = fetch(wd)
        os.chdir(path)
        if path not in sys.path:
            sys.path.insert(0, path)
        logger.info("runtime_env: working_dir %s", path)


def _ensure_venv(pip: Dict[str, Any], cache: str) -> str:
    """Build (or reuse) the content-addressed venv for a pip spec.
    Creation is offline: `pip install --no-index --find-links
    <wheelhouse>`. Concurrent workers building the same env serialize on
    an fcntl lock; the finished venv is moved into place atomically so a
    crashed build never half-caches."""
    import fcntl
    import shutil
    import subprocess
    import tempfile

    env_hash = pip.get("env_hash") or pip_env_hash(pip)
    dest = os.path.join(cache, f"venv-{env_hash}")
    if os.path.isdir(dest):
        return dest
    if not os.path.isdir(pip["wheelhouse"]):
        # Wheelhouses are LOCAL paths, deliberately not shipped through
        # the GCS KV (they can dwarf the blob store): on multi-host
        # clusters they must exist at the same path on every node
        # (shared filesystem or baked into the image).
        raise RuntimeError(
            f"pip wheelhouse {pip['wheelhouse']!r} does not exist on "
            f"this node; wheelhouses must be present at the same path "
            f"on every node (shared FS or machine image)")
    os.makedirs(cache, exist_ok=True)
    lock_path = os.path.join(cache, f"venv-{env_hash}.lock")
    with open(lock_path, "w") as lock_f:
        fcntl.flock(lock_f, fcntl.LOCK_EX)
        if os.path.isdir(dest):  # another worker won the build
            return dest
        tmp = tempfile.mkdtemp(prefix=f"venv-{env_hash}.", dir=cache)
        try:
            # Activation is a sys.path prefix in the SAME interpreter
            # (the base environment stays visible underneath), so the
            # "venv" needs only a site-packages dir for pip --target —
            # no interpreter copy, no `python -m venv` subprocess.
            os.makedirs(_venv_site_packages(tmp), exist_ok=True)
            subprocess.run(
                [sys.executable, "-m", "pip", "install", "--no-index",
                 "--find-links", pip["wheelhouse"],
                 "--target", _venv_site_packages(tmp),
                 *pip["packages"]],
                check=True, capture_output=True, timeout=600)
            os.rename(tmp, dest)
        except subprocess.CalledProcessError as e:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"runtime_env pip install failed for {pip['packages']}: "
                f"{(e.stderr or b'').decode(errors='replace')[-800:]}"
            ) from None
        except subprocess.TimeoutExpired:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"runtime_env pip install timed out for {pip['packages']}"
            ) from None
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.isdir(dest):
                raise
    return dest


def _venv_site_packages(venv_dir: str) -> str:
    return os.path.join(
        venv_dir, "lib",
        f"python{sys.version_info.major}.{sys.version_info.minor}",
        "site-packages")


def _activate_venv(venv_dir: str) -> None:
    """In-process activation: the venv's site-packages gets import
    priority. (The reference re-launches the worker under the venv's
    interpreter; this framework's workers materialize envs after spawn,
    before any user import, which the sys.path prefix covers.)"""
    site = _venv_site_packages(venv_dir)
    if site not in sys.path:
        sys.path.insert(0, site)
    os.environ["VIRTUAL_ENV"] = venv_dir
    logger.info("runtime_env: venv %s", venv_dir)
