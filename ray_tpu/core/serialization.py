"""Serialization: cloudpickle + pickle protocol 5 out-of-band buffers.

Equivalent of the reference's `SerializationContext`
(`python/ray/_private/serialization.py:108`) + vendored cloudpickle: values are
pickled with protocol 5; large contiguous buffers (numpy arrays, jax host
arrays) are carried out-of-band so readers can map them zero-copy from shared
memory. Exceptions are wrapped so the remote traceback survives the boundary.

Wire layout of a serialized value:

    [8B magic+version][msgpack header: {p: pickle_len, b: [buffer lengths]}]
    [pickle bytes][buffer 0 (8B aligned)][buffer 1]...
"""

from __future__ import annotations

import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle
import msgpack

_MAGIC = b"RTPU\x01\x00\x00\x00"
_ALIGN = 8


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


# Above this size the opcode walk itself could cost milliseconds on the
# submit hot path (many-opcode object graphs); callers pick the safe
# answer for oversized payloads instead of scanning.
_REFS_MAIN_SCAN_MAX = 256 * 1024


def _refs_main(payload: bytes) -> bool:
    """Does this pickle reference the __main__ MODULE (a by-reference
    global, unresolvable on a peer) — as opposed to merely containing the
    byte literal inside an embedded data blob (pre-serialized function
    bytes ride inside TaskSpecs on every submit, and a substring hit
    there must NOT force the 2.5x cloudpickle fallback)? The substring
    scan is the cheap gate (no hit, no cost); on a hit, a pickletools
    opcode walk looks for a standalone '__main__' string — module refs
    surface as GLOBAL/unicode opcodes, while blob content stays inside a
    single bytes-opcode argument. Errs toward cloudpickle on anything
    unexpected. Payloads over the scan cap skip the walk and report True
    unscanned: oversized hits are rare (function blobs that big are
    unusual, args travel separately), and paying the cloudpickle fallback
    there is safe — assuming 'blob content' would silently reopen the
    peer-side AttributeError this guard exists to prevent.

    Known tradeoff: the walk re-runs per message even for an identical
    embedded blob (no memoization — control payloads are fresh bytes each
    time, so a verdict cache would have to hash the payload, which costs
    about as much as the walk it saves). Bounded by the scan cap."""
    if b"__main__" not in payload:
        return False
    if len(payload) > _REFS_MAIN_SCAN_MAX:
        return True
    try:
        import pickletools

        for op, arg, _pos in pickletools.genops(payload):
            name = op.name
            if name == "GLOBAL":
                if isinstance(arg, str) and arg.startswith("__main__"):
                    return True
            elif "UNICODE" in name and arg == "__main__":
                return True
        return False
    except Exception:  # noqa: BLE001 — be safe, capture by value
        return True


def serialize(value: Any) -> List[memoryview | bytes]:
    """Serialize to a list of buffers (header + pickle + OOB buffers).

    Returns a buffer list suitable for vectored writes; total size is
    sum(len(b) padded to 8) for the OOB region.
    """
    oob: List[pickle.PickleBuffer] = []

    def callback(buf: pickle.PickleBuffer):
        oob.append(buf)
        return False  # out-of-band

    # C-pickle fast path (~2.5x cheaper than cloudpickle and this is every
    # task arg / put value / return). Plain pickle serializes driver-script
    # classes BY REFERENCE ("__main__.X") — dumps fine here, unresolvable
    # on the peer — so any payload referencing __main__ falls back to
    # cloudpickle's by-value capture. Closures/lambdas/locals fail the
    # plain dump outright and fall back the same way.
    try:
        payload = pickle.dumps(value, protocol=5, buffer_callback=callback)
        if _refs_main(payload):
            raise ValueError("by-reference __main__ pickle")
    except Exception:  # noqa: BLE001 — retry by value
        oob.clear()
        payload = cloudpickle.dumps(value, protocol=5, buffer_callback=callback)
    raw_views: List[memoryview] = []
    lens: List[int] = []
    for b in oob:
        m = b.raw()
        if not m.contiguous:
            m = memoryview(bytes(b))
        else:
            m = m.cast("B")
        raw_views.append(m)
        lens.append(m.nbytes)
    header = msgpack.packb({"p": len(payload), "b": lens})
    parts: List[memoryview | bytes] = [
        _MAGIC + struct.pack("<I", len(header)),
        header,
        payload,
    ]
    # Pad pickle so OOB buffers start aligned.
    pos = len(_MAGIC) + 4 + len(header) + len(payload)
    for m in raw_views:
        pad = _align(pos) - pos
        if pad:
            parts.append(b"\x00" * pad)
            pos += pad
        parts.append(m)
        pos += m.nbytes
    return parts


def serialized_size(parts: List[memoryview | bytes]) -> int:
    return sum(p.nbytes if isinstance(p, memoryview) else len(p) for p in parts)


def serialize_to_bytes(value: Any) -> bytes:
    return b"".join(bytes(p) if isinstance(p, memoryview) else p for p in serialize(value))


def deserialize(data: memoryview | bytes, zero_copy: bool = True) -> Any:
    """Deserialize from a contiguous buffer. When ``zero_copy`` and ``data``
    is a memoryview backed by shared memory, numpy arrays reference the shm
    pages directly (read-only semantics are the caller's contract)."""
    view = memoryview(data).cast("B")
    if bytes(view[:4]) != _MAGIC[:4]:
        raise ValueError("Corrupt serialized value (bad magic)")
    (hlen,) = struct.unpack("<I", view[8:12])
    header = msgpack.unpackb(bytes(view[12 : 12 + hlen]))
    pos = 12 + hlen
    payload = view[pos : pos + header["p"]]
    pos += header["p"]
    buffers = []
    for blen in header["b"]:
        pos = _align(pos)
        b = view[pos : pos + blen]
        if not zero_copy:
            b = memoryview(bytes(b))
        else:
            # Zero-copy readers alias shared-memory pages: hand out read-only
            # views so a consumer mutating e.g. a numpy array cannot corrupt
            # the object for other readers (reference: plasma buffers are
            # read-only after seal).
            b = b.toreadonly()
        buffers.append(b)
        pos += blen
    from ray_tpu.object_ref import _BorrowScope

    with _BorrowScope():
        return pickle.loads(bytes(payload), buffers=buffers)


def dumps(value: Any) -> bytes:
    """Plain in-band cloudpickle (for user functions/classes, which must
    be captured BY VALUE — a __main__-defined function pickled by
    reference would dump fine here and fail to import on the worker)."""
    return cloudpickle.dumps(value)


def dumps_ctrl(value: Any) -> bytes:
    """Control-plane envelope serializer: C-pickle first (2.5x faster than
    cloudpickle on a TaskSpec, and this runs on every RPC), cloudpickle
    only when plain pickle cannot (closures, locals). Safe because control
    messages carry framework types and PRE-SERIALIZED user blobs only —
    user functions/classes/args all flow as bytes produced by dumps()/
    serialize() upstream, never as live objects. Same `__main__` guard as
    serialize(): plain pickle captures driver-script types BY REFERENCE,
    which dumps fine here and explodes peer-side with an AttributeError
    nobody can act on — fall back to cloudpickle's by-value capture."""
    try:
        payload = pickle.dumps(value, protocol=5)
        if _refs_main(payload):
            raise ValueError("by-reference __main__ pickle")
    except Exception:  # noqa: BLE001 — closure/local/__main__ in envelope
        return cloudpickle.dumps(value)
    return payload


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def serialize_exception(exc: BaseException, function_name: str = "") -> bytes:
    """Serialize an exception as a framed value (so error blobs can double as
    object-store values: the reference stores RayTaskError AS the object so
    dependent tasks schedule and then raise). Falls back when unpicklable."""
    import traceback

    from ray_tpu.exceptions import RayTaskError

    if isinstance(exc, RayTaskError):
        # Already wrapped upstream (error object flowed through a dependency):
        # re-serialize as-is so the original cause's type survives.
        return serialize_to_bytes(RayTaskError(exc.function_name,
                                               exc.traceback_str, exc.cause))
    tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
    try:
        cause: Exception | None = exc if isinstance(exc, Exception) else None
        err = RayTaskError(function_name, tb, cause)
        return serialize_to_bytes(err)
    except Exception:
        err = RayTaskError(function_name, tb, None)
        return serialize_to_bytes(err)


def deserialize_exception(data: bytes):
    try:
        return deserialize(data, zero_copy=False)
    except Exception as e:  # unpicklable user exception type on this side
        from ray_tpu.exceptions import RaySystemError

        return RaySystemError(f"Failed to deserialize remote error: {e}")
