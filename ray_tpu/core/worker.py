"""Worker process: executes tasks and hosts actors.

Equivalent of the reference's Python worker (`python/ray/_private/workers/
default_worker.py` + the execution half of CoreWorker, `core_worker.cc:2529`
ExecuteTask and the scheduling queues in `core_worker/transport/`):

- Normal tasks arrive as pushes from the raylet over the registration
  connection and run on a single executor thread.
- Actor method calls arrive on the worker's *direct* RPC server, one
  connection per caller. Per-connection handler threads give per-caller FIFO;
  an executor sized by `max_concurrency` runs them (async `async def` methods
  run on an asyncio loop, matching the reference's async actors on fibers,
  `core_worker/fiber.h`).
- Results: small values returned inline; large values sealed straight into
  the node's shared-memory store.

TPU note: a worker granted TPU resources receives `RAY_TPU_GRANTED_TPU`;
jax is imported lazily by user code, so a plain CPU worker never pays the
jax import or chip-lock cost.
"""

from __future__ import annotations

import asyncio
import logging
import os
import queue
import signal
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.common import TaskSpec
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu.core.rpc import DEFERRED, Connection, RpcServer
from ray_tpu.core.runtime import CoreRuntime
from ray_tpu.observability import tracing as _tracing

logger = logging.getLogger(__name__)


class WorkerRuntime(CoreRuntime):
    """CoreRuntime + task execution loop."""

    def __init__(self):
        worker_id = WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"])
        # Items: (spec, reply_conn) — reply_conn None for raylet-dispatched
        # tasks (completion via task_done), set for direct-lease pushes
        # (completion via task_result push on that connection).
        self._task_queue: "queue.Queue" = queue.Queue()
        # Direct server must exist before registration (address is reported).
        self.direct_server = RpcServer(name="worker-direct")
        self.direct_server.register("actor_call", self._handle_actor_call)
        self.direct_server.register("actor_call_light",
                                    self._handle_actor_call_light)
        self.direct_server.register_raw("serve_raw", self._handle_serve_raw)
        self.direct_server.register_raw("serve_stream",
                                        self._handle_serve_stream)
        self.direct_server.register("direct_call", self._handle_direct_call)
        self.direct_server.register("direct_call_batch",
                                    self._handle_direct_call_batch)
        self.direct_server.register("cancel_direct", self._handle_cancel_direct)
        self.direct_server.register("cancel_actor_task",
                                    self._handle_cancel_actor_task)
        self.direct_server.start()
        self._cancelled_direct: set = set()
        # Direct-result coalescing: completed lease-task results buffered
        # per owner connection and flushed as ONE task_result_batch frame
        # by a tick-bounded flusher thread (started on first use) — burst
        # completions share a frame, while any single result is delayed
        # by at most the flush tick, never by the NEXT task's runtime.
        # Entries are popped on every flush and on connection failure.
        self._direct_reply_buf: Dict[Connection, list] = {}
        self._direct_reply_lock = threading.Lock()
        self._direct_reply_event = threading.Event()
        self._direct_reply_flusher: Optional[threading.Thread] = None
        # task_id -> (future, caller conn, spec) for in-flight actor calls,
        # so cancel_actor_task can cancel queued (and async running) work.
        self._actor_calls: Dict[bytes, tuple] = {}
        # Cancellation reply dedup: fut.cancel() on a coroutine future can
        # return True while the body is mid-execution (run_coroutine_
        # threadsafe futures never enter RUNNING), so the cancel handler
        # and the coroutine's own error path may both try to reply.
        self._replied: set = set()
        # Cancels that arrived while their call was in the submit window
        # (registered in _actor_calls but future not yet created).
        self._cancel_requested: set = set()
        self._reply_lock = threading.Lock()
        super().__init__(
            gcs_address=os.environ["RAY_TPU_GCS_ADDRESS"],
            raylet_address=os.environ["RAY_TPU_RAYLET_ADDRESS"],
            session_suffix=os.environ["RAY_TPU_SESSION"],
            node_id=NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"]),
            job_id=JobID.nil(),
            worker_id=worker_id,
            is_driver=False,
        )
        self.current_task_id = TaskID.for_task(JobID.nil())
        self._fn_cache: Dict[str, Any] = {}
        # Deserialized-value cache for owner-deduped immutable args
        # (kind "c"): blob -> value, LRU-capped in _resolve_args.
        from collections import OrderedDict as _OD

        self._arg_value_cache: "_OD" = _OD()
        # Actor state
        self.actor_instance: Any = None
        self.actor_spec: Optional[TaskSpec] = None
        self._actor_executor: Optional[Any] = None
        self._async_loop: Optional[asyncio.AbstractEventLoop] = None
        self._stopping = threading.Event()
        self._cancel_task_id = None  # ray.cancel target (see on_cancel_exec)
        # Task-event batching (reference task_event_buffer_.h: events are
        # buffered and flushed on an interval, never sent per task — an
        # inline RPC per task costs more than dispatching the task).
        self._event_buf: list = []
        self._event_lock = threading.Lock()
        self._event_flusher = threading.Thread(
            target=self._event_flush_loop, name="task-event-flush",
            daemon=True)
        self._event_flusher.start()

    def _buffer_task_events(self, events: list):
        with self._event_lock:
            self._event_buf.extend(events)

    def _event_flush_loop(self, period_s: float = 1.0):
        while not self._stopping.wait(period_s):
            self._flush_task_events()
        # Final drain: the last tasks before a graceful exit must still
        # reach the timeline/state API (they were sent inline pre-batching).
        self._flush_task_events()

    def _flush_task_events(self):
        with self._event_lock:
            batch, self._event_buf = self._event_buf, []
        if not batch:
            return
        try:
            self.raylet.call_async("direct_task_event", {"events": batch})
        except Exception:  # noqa: BLE001 — observability only
            pass

    # ------------------------------------------------------------ plumbing

    def register(self):
        resp = self.raylet.call(
            "register_worker",
            {"worker_id": self.worker_id, "pid": os.getpid(),
             "direct_address": self.direct_server.address})
        if not resp.get("ok"):
            raise RuntimeError("raylet refused worker registration")

    def on_execute_task(self, spec: TaskSpec):
        # Called on the RpcClient reader thread: enqueue only.
        self._task_queue.put((spec, None))

    def _handle_direct_call(self, conn: Connection, data: Dict[str, Any]):
        """A lease holder pushes a normal task over the direct channel
        (reference: PushTask on a leased worker, direct_task_transport).
        Execution happens on the main task thread, FIFO with raylet work."""
        self._task_queue.put((data["spec"], conn))
        return {"accepted": True}

    def _handle_direct_call_batch(self, conn: Connection,
                                  data: Dict[str, Any]):
        """Submission bursts arrive as one framed message carrying many
        specs — per-task framing/syscall overhead dominates small-task
        throughput otherwise (reference batches lease-side pushes too)."""
        for spec in data["specs"]:
            self._task_queue.put((spec, conn))
        return {"accepted": len(data["specs"])}

    def _handle_cancel_direct(self, conn: Connection, data: Dict[str, Any]):
        task_id = data["task_id"]
        spec = self.executing_task
        if spec is not None and spec.task_id == task_id:
            self._cancelled_direct.add(task_id.binary())
            self.on_cancel_exec(task_id)
            return {}
        # Only mark queued targets: a cancel racing past completion must
        # not leak an entry that nothing will ever discard.
        with self._task_queue.mutex:
            queued = any(s.task_id == task_id
                         for s, _conn in self._task_queue.queue)
        if queued:
            self._cancelled_direct.add(task_id.binary())
        return {}

    def on_cancel_exec(self, task_id):
        """ray.cancel: record the target and poke the main thread; the
        SIGUSR1 handler raises only if the target is still executing."""
        self._cancel_task_id = task_id
        import signal as _signal

        os.kill(os.getpid(), _signal.SIGUSR1)

    def main_loop(self):
        while not self._stopping.is_set():
            try:
                spec, reply_conn = self._task_queue.get(timeout=1.0)
            except queue.Empty:
                if self.raylet.is_closed:
                    logger.info("raylet connection closed; worker exiting")
                    return
                continue
            if reply_conn is None:
                self._execute(spec)
            else:
                self._execute_direct(spec, reply_conn)
            if getattr(self, "_env_setup_error", None):
                # The failure has been delivered to exactly one task (as
                # RuntimeEnvSetupError); exit so this poisoned worker
                # leaves the pool — a retry gets a FRESH worker whose env
                # build may succeed, instead of re-leasing this one and
                # failing the same env forever.
                logger.error("exiting after runtime_env setup failure")
                self._stopping.set()
                return

    # ----------------------------------------------------------- execution

    def _resolve_function(self, spec: TaskSpec):
        if spec.function_blob is not None:
            return serialization.loads(spec.function_blob)
        fn_id = spec.function_id
        fn = self._fn_cache.get(fn_id)
        if fn is None:
            resp = self.gcs.call("kv_get", {"namespace": "fn", "key": fn_id.encode()})
            blob = resp["value"]
            if blob is None:
                raise RuntimeError(f"function {fn_id} not found in GCS function table")
            fn = serialization.loads(blob)
            # The exported-function cache (one entry per distinct
            # @remote definition, same as the reference's function
            # table): bounded by driver code size.
            # raylint: disable=RL011 — bounded by @remote definitions
            self._fn_cache[fn_id] = fn
        return fn

    def _resolve_args(self, spec: TaskSpec) -> Tuple[list, dict]:
        # Batch-fetch every ref arg in ONE get: a reduce-style task taking
        # n refs (push shuffle fan-in) must not pay n sequential fetch
        # round trips.
        ref_ids = [payload for kind, payload in spec.args
                   if kind not in ("v", "c")]
        fetched = iter(self.get(ref_ids)) if ref_ids else iter(())
        values = []
        arg_cache = self._arg_value_cache
        for kind, payload in spec.args:
            if kind == "c":
                # Owner-deduped immutable leaf (str/bytes/int/float/bool/
                # None — see CoreRuntime.serialize_args): safe to share
                # the deserialized value across tasks, so repeat args cost
                # a dict hit instead of a pickle parse.
                val = arg_cache.get(payload)
                if val is None and payload not in arg_cache:
                    val = serialization.deserialize(payload)
                    arg_cache[payload] = val
                    cap = max(1, GLOBAL_CONFIG.arg_dedupe_cache_entries)
                    while len(arg_cache) > cap:
                        arg_cache.popitem(last=False)
                else:
                    arg_cache.move_to_end(payload)
                values.append(val)
            elif kind == "v":
                values.append(serialization.deserialize(payload))
            else:
                values.append(next(fetched))
        nk = len(spec.kwargs_keys)
        if nk:
            pos, kwvals = values[:-nk], values[-nk:]
            return pos, dict(zip(spec.kwargs_keys, kwvals))
        return values, {}

    def _run_task_body(self, spec: TaskSpec
                       ) -> Tuple[List[Dict[str, Any]], Optional[bytes]]:
        """Shared execution core for raylet-dispatched and direct tasks:
        resolve args + function, run (awaiting coroutines), store results.
        Returns (results, error_blob)."""
        self.executing_task = spec
        # Children submitted by the body join this task's trace.
        self.set_trace_ctx(spec.trace_ctx)
        # The span ADOPTS the spec's ids: the submitter minted them, so
        # the executed span and the caller's parent edge line up.
        span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            span = _tracing.get_tracer().start_span(
                "task.run", ctx=spec.trace_ctx, attrs={"task": spec.name})
        results: List[Dict[str, Any]] = []
        error_blob: Optional[bytes] = None
        trace_err: Optional[str] = None
        try:
            if getattr(self, "_env_setup_error", None):
                from ray_tpu.exceptions import RuntimeEnvSetupError

                raise RuntimeEnvSetupError(
                    f"runtime_env setup failed on this worker: "
                    f"{self._env_setup_error}")
            args, kwargs = self._resolve_args(spec)
            if spec.actor_creation:
                cls = serialization.loads(spec.actor_class_blob)
                self.actor_instance = cls(*args, **kwargs)
                restart_count = getattr(spec, "actor_restart_count", 0)
                if restart_count > 0:
                    # State-restore hook: this is incarnation N of a
                    # max_restarts actor — __init__ re-ran with the
                    # original args, and the hook lets the class rebuild
                    # state __init__ cannot (reload a checkpoint,
                    # re-subscribe). A raising hook fails the creation
                    # (the GCS declares the actor dead) — a half-restored
                    # actor must never serve calls.
                    hook = getattr(self.actor_instance,
                                   "__ray_restart__", None)
                    if hook is not None:
                        hook(restart_count)
                self.actor_spec = spec
                self._setup_actor_executor(spec.actor_max_concurrency)
                values = []
            else:
                fn = self._resolve_function(spec)
                out = fn(*args, **kwargs)
                if asyncio.iscoroutine(out):
                    out = asyncio.new_event_loop().run_until_complete(out)
                values = self._pack_returns(spec, out)
            results = [self._store_result(oid, v)
                       for oid, v in zip(spec.return_ids(), values)]
        except BaseException as e:  # noqa: BLE001 - worker must survive user errors
            error_blob = serialization.serialize_exception(e, spec.name)
            trace_err = f"{type(e).__name__}: {e}"
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                self._stopping.set()
        finally:
            self.executing_task = None
            span.end(error=trace_err)
            self.set_trace_ctx(None)
        return results, error_blob

    def _execute(self, spec: TaskSpec):
        results, error_blob = self._run_task_body(spec)
        try:
            # Pipelined: the worker is free for the next task the moment the
            # report is on the wire; failures surface via the callback.
            self.raylet.call_async(
                "task_done",
                {"task_id": spec.task_id, "results": results,
                 "error": error_blob},
                lambda env, _p: logger.error(
                    "task_done for %s failed: %s", spec.name, env.get("e"))
                if (env.get("e") or env.get("_lost")) else None)
        except Exception:
            logger.exception("failed to report task_done")

    def _execute_direct(self, spec: TaskSpec, conn: Connection):
        """Run a lease-pushed normal task; reply straight to the owner
        (inline results) / seal large results into the node store. The
        raylet never sees the task, so the worker reports its lifecycle
        events (timeline/state API parity with raylet-dispatched tasks)."""
        import time as _time

        from ray_tpu.exceptions import TaskCancelledError

        started = _time.time()
        if spec.task_id.binary() in self._cancelled_direct:
            self._cancelled_direct.discard(spec.task_id.binary())
            self._reply_direct_result(
                conn, spec, [],
                serialization.serialize_exception(
                    TaskCancelledError(spec.task_id), spec.name))
            return
        try:
            results, error_blob = self._run_task_body(spec)
        finally:
            self._cancelled_direct.discard(spec.task_id.binary())
        self._reply_direct_result(conn, spec, results, error_blob)
        base = {
            "task_id": spec.task_id.hex(), "name": spec.name,
            "node_id": os.environ.get("RAY_TPU_NODE_ID", "")[:12],
            "worker_id": self.worker_id.hex()[:12], "pid": os.getpid(),
            "queued_at": spec.submitted_at,
            **(spec.trace_ctx or {}),
        }
        self._buffer_task_events([
            dict(base, state="RUNNING", ts=started),
            dict(base, state="FAILED" if error_blob is not None
                 else "FINISHED", ts=_time.time()),
        ])

    def _pack_returns(self, spec: TaskSpec, out: Any) -> List[Any]:
        if spec.num_returns == 1:
            return [out]
        if spec.num_returns == 0:
            return []
        vals = list(out)
        if len(vals) != spec.num_returns:
            raise ValueError(
                f"Task {spec.name} declared num_returns={spec.num_returns} but "
                f"returned {len(vals)} values")
        return vals

    def _store_result(self, oid: ObjectID, value: Any) -> Dict[str, Any]:
        from ray_tpu.object_ref import _NestedRefCapture

        with _NestedRefCapture() as captured:
            parts = serialization.serialize(value)
        if captured:
            # Return value embeds ObjectRefs: pin them to the result
            # container's lifetime BEFORE replying — this worker's own
            # borrows drop as soon as its locals go out of scope, which can
            # be before the caller deserializes the result.
            self._register_container_refs(oid, captured)
        size = serialization.serialized_size(parts)
        if size <= GLOBAL_CONFIG.object_inline_max_bytes:
            blob = b"".join(bytes(p) if isinstance(p, memoryview) else p for p in parts)
            return {"object_id": oid, "kind": "inline", "data": blob}
        self._write_segment(oid, parts, size)
        return {"object_id": oid, "kind": "store", "size": size}

    # -------------------------------------------------------------- actors

    def _setup_actor_executor(self, max_concurrency: int):
        from concurrent.futures import ThreadPoolExecutor

        self._actor_executor = ThreadPoolExecutor(
            max_workers=max(1, max_concurrency), thread_name_prefix="actor-exec")
        loop = asyncio.new_event_loop()
        self._async_loop = loop
        threading.Thread(target=loop.run_forever, name="actor-asyncio",
                         daemon=True).start()

    def _handle_actor_call(self, conn: Connection, data: Dict[str, Any]):
        spec: TaskSpec = data["spec"]
        if self.actor_instance is None:
            raise RuntimeError("actor not initialized")
        method = getattr(self.actor_instance, spec.method_name, None)
        if method is None and spec.method_name != "__ray_terminate__":
            # A task-level error, not a transport error: the caller gets an
            # AttributeError on get() and the actor stays alive.
            err = serialization.serialize_exception(
                AttributeError(f"actor {type(self.actor_instance).__name__!r} "
                               f"has no method {spec.method_name!r}"), spec.name)
            self._reply_actor_result(conn, spec, [], err)
            return {"accepted": True}
        if spec.method_name == "__ray_terminate__":
            self._actor_executor.submit(self._run_actor_method, conn, spec,
                                        method or (lambda: None))
            return {"accepted": True}
        tid = spec.task_id.binary()
        # Register BEFORE submitting: the method's finally-pop must find
        # the entry even when a trivial body finishes before this handler
        # resumes (a post-submit insert would leak the entry forever).
        with self._reply_lock:
            self._actor_calls[tid] = (None, conn, spec)
        if asyncio.iscoroutinefunction(getattr(method, "__func__", method)):
            fut = asyncio.run_coroutine_threadsafe(
                self._run_actor_method_async(conn, spec, method), self._async_loop)
        else:
            fut = self._actor_executor.submit(
                self._run_actor_method, conn, spec, method)
        with self._reply_lock:
            if tid in self._actor_calls:  # not yet completed
                self._actor_calls[tid] = (fut, conn, spec)
            pending_cancel = tid in self._cancel_requested
            self._cancel_requested.discard(tid)
        if pending_cancel:
            # A cancel arrived in the submit window (between registration
            # and future creation): complete it now instead of dropping it.
            self._try_cancel_actor_call(tid, fut, conn, spec)
        return {"accepted": True}

    def _handle_actor_call_light(self, conn: Connection, data: Dict[str, Any]):
        """Lean request/response actor invocation — no TaskSpec, no
        ObjectRefs, no lineage, result rides the RPC response itself.

        The actor-task machinery costs ~10x a raw RPC round trip (spec
        build + arg framing + record/ref bookkeeping on the caller, spec
        decode + reply push + task events here), which is pure overhead
        for high-rate stateless dispatch like the Serve proxy's
        per-request hop (the reference's proxy pays the equivalent C++
        fast path, `core_worker` direct actor submit). Semantics kept:
        runs on the actor executor (max_concurrency respected, async
        methods on the actor loop); dropped: ordering, cancellation,
        retries, task events — callers that need those use the full
        actor_call. Caller contract: args must not reference driver
        ``__main__`` types (serialize() falls back to by-value capture,
        so in practice any picklable args work)."""
        mid = conn.current_msg_id
        name = data["m"]
        if self.actor_instance is None:
            raise RuntimeError("actor not initialized")
        method = getattr(self.actor_instance, name, None)
        if method is None:
            raise AttributeError(
                f"actor {type(self.actor_instance).__name__!r} "
                f"has no method {name!r}")
        args = serialization.deserialize(data["a"]) if data.get("a") else ()
        kwargs = serialization.deserialize(data["kw"]) if data.get("kw") else {}

        def reply_ok(out):
            conn.reply(mid, "actor_call_light",
                       {"r": serialization.serialize_to_bytes(out)})

        def reply_err(e: BaseException):
            conn.reply(mid, "actor_call_light",
                       {"err": serialization.serialize_exception(e, name)})

        if asyncio.iscoroutinefunction(getattr(method, "__func__", method)):
            # Trace context crosses into the loop automatically:
            # run_coroutine_threadsafe schedules via call_soon_threadsafe,
            # which snapshots THIS thread's contextvars (set by the RPC
            # server from the envelope's wire context).
            async def run_async():
                try:
                    reply_ok(await method(*args, **kwargs))
                except BaseException as e:  # noqa: BLE001 — delivered to caller
                    reply_err(e)
            asyncio.run_coroutine_threadsafe(run_async(), self._async_loop)
        else:
            # Executor threads do NOT inherit contextvars: hand the wire
            # trace context across explicitly (None when tracing is off).
            tctx = _tracing.capture()

            def run():
                try:
                    if _tracing._ENABLED:
                        # Unconditional when tracing: also CLEARS any
                        # stale context a previous request left on this
                        # pooled executor thread.
                        _tracing.set_current(tctx)
                    reply_ok(method(*args, **kwargs))
                except BaseException as e:  # noqa: BLE001 — delivered to caller
                    reply_err(e)
            self._actor_executor.submit(run)
        return DEFERRED

    def _dispatch_serve_raw(self, conn: Connection, payload: bytes,
                            method: str, hook_name: str):
        """Shared core of the serve fast-lane raw handlers: hand the raw
        frame to the actor instance's dispatch hook on its asyncio loop
        and reply with the raw parts it returns.

        Reply discipline (raylint RL001): pre-schedule failures raise
        BEFORE the DEFERRED return — the server loop converts them to an
        error reply (the fast lane reads that as provably-not-executed
        and falls back). Once the coroutine is scheduled, IT owns the
        reply: every exit path of `run` replies, errors included (an
        error frame, not a transport error — user-code failures ride
        inside the frame so one bad request cannot poison a coalesced
        batch)."""
        from ray_tpu.serve import dataplane

        mid = conn.current_msg_id
        inst = self.actor_instance
        loop = self._async_loop
        hook = getattr(inst, hook_name, None) if inst is not None else None
        if hook is None or loop is None:
            raise RuntimeError(
                f"actor is not a serve replica (no {hook_name})")
        view = memoryview(payload)

        async def run():
            try:
                parts = await hook(view)
            except BaseException as e:  # noqa: BLE001 — delivered as error frame
                try:
                    conn.reply_raw(mid, method,
                                   dataplane.encode_error_frame(e))
                except Exception:  # noqa: BLE001 — caller gone; its client
                    pass           # delivers the loss
                return
            try:
                conn.reply_raw(mid, method, parts)
            except Exception:  # noqa: BLE001 — caller gone mid-reply
                pass

        asyncio.run_coroutine_threadsafe(run(), loop)
        return DEFERRED

    def _handle_serve_raw(self, conn: Connection, payload: bytes):
        """Serve fast-lane request frame: raw bytes end to end (no pickle
        of request/response bodies). The frame carries 1..N coalesced
        requests; the replica's dispatch hook answers them all in one
        reply frame."""
        return self._dispatch_serve_raw(conn, payload, "serve_raw",
                                        "__serve_raw_dispatch__")

    def _handle_serve_stream(self, conn: Connection, payload: bytes):
        """Serve fast-lane stream pull: drains a replica-side stream
        queue as raw chunk frames (the token-stream consumer path)."""
        return self._dispatch_serve_raw(conn, payload, "serve_stream",
                                        "__serve_stream_raw__")

    def _try_cancel_actor_call(self, tid: bytes, fut, caller_conn: Connection,
                               spec: TaskSpec) -> bool:
        """Cancel a queued (or async mid-run — see _replied) call and report
        the cancellation; the _replied guard suppresses a duplicate reply
        from a coroutine that was actually executing."""
        cancelled = fut.cancel()
        if cancelled:
            from ray_tpu.exceptions import TaskCancelledError

            with self._reply_lock:
                self._actor_calls.pop(tid, None)
                self._replied.add(tid)
                if len(self._replied) > 4096:
                    # Stale never-ran entries; ids never recur, and a
                    # dropped in-flight entry only risks a duplicate push
                    # the caller already ignores.
                    self._replied.clear()
                    self._replied.add(tid)
            self._reply_actor_result(
                caller_conn, spec, [],
                serialization.serialize_exception(
                    TaskCancelledError(spec.task_id), spec.name))
        return cancelled

    def _handle_cancel_actor_task(self, conn: Connection, data: Dict[str, Any]):
        """ray.cancel on an actor task: queued calls are dropped (caller
        gets TaskCancelledError); async running calls get CancelledError
        at their next await; sync running calls are uninterruptible
        (reference semantics: only queued/async actor tasks cancel)."""
        tid = data["task_id"].binary()
        with self._reply_lock:
            rec = self._actor_calls.get(tid)
            if rec is not None and rec[0] is None:
                # Submit window: the call is registered but its future
                # doesn't exist yet. Mark it; the post-submit
                # re-registration in _handle_actor_call completes the
                # cancellation instead of silently no-opping.
                self._cancel_requested.add(tid)
                return {"cancelled": True}
        if rec is None:
            return {"cancelled": False}
        fut, caller_conn, spec = rec
        return {"cancelled":
                self._try_cancel_actor_call(tid, fut, caller_conn, spec)}

    def _reply_actor_result_once(self, conn: Connection, spec: TaskSpec,
                                 results, error_blob):
        with self._reply_lock:
            if spec.task_id.binary() in self._replied:
                self._replied.discard(spec.task_id.binary())
                return  # cancel handler already answered this task
        self._reply_actor_result(conn, spec, results, error_blob)

    def _run_actor_method(self, conn: Connection, spec: TaskSpec, method):
        results: List[Dict[str, Any]] = []
        error_blob: Optional[bytes] = None
        trace_err: Optional[str] = None
        self.set_trace_ctx(spec.trace_ctx)
        span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            span = _tracing.get_tracer().start_span(
                "actor.call", ctx=spec.trace_ctx,
                attrs={"method": spec.method_name})
        try:
            if spec.method_name == "__ray_terminate__":
                self._graceful_exit(conn, spec)
                return
            args, kwargs = self._resolve_args(spec)
            out = method(*args, **kwargs)
            values = self._pack_returns(spec, out)
            results = [self._store_result(oid, v)
                       for oid, v in zip(spec.return_ids(), values)]
        except BaseException as e:  # noqa: BLE001
            error_blob = serialization.serialize_exception(e, spec.name)
            trace_err = f"{type(e).__name__}: {e}"
        finally:
            span.end(error=trace_err)
            self.set_trace_ctx(None)
            with self._reply_lock:
                self._actor_calls.pop(spec.task_id.binary(), None)
        self._reply_actor_result_once(conn, spec, results, error_blob)

    async def _run_actor_method_async(self, conn: Connection, spec: TaskSpec, method):
        results: List[Dict[str, Any]] = []
        error_blob: Optional[bytes] = None
        trace_err: Optional[str] = None
        self.set_trace_ctx(spec.trace_ctx)
        span = _tracing.NOOP_SPAN
        if _tracing._ENABLED:
            span = _tracing.get_tracer().start_span(
                "actor.call", ctx=spec.trace_ctx,
                attrs={"method": spec.method_name})
        try:
            args, kwargs = self._resolve_args(spec)
            out = await method(*args, **kwargs)
            values = self._pack_returns(spec, out)
            results = [self._store_result(oid, v)
                       for oid, v in zip(spec.return_ids(), values)]
        except asyncio.CancelledError:
            # ray.cancel on a running async actor task: surface the typed
            # cancellation, not a bare CancelledError.
            from ray_tpu.exceptions import TaskCancelledError

            error_blob = serialization.serialize_exception(
                TaskCancelledError(spec.task_id), spec.name)
            trace_err = "TaskCancelledError"
        except BaseException as e:  # noqa: BLE001
            error_blob = serialization.serialize_exception(e, spec.name)
            trace_err = f"{type(e).__name__}: {e}"
        finally:
            span.end(error=trace_err)
            self.set_trace_ctx(None)
            with self._reply_lock:
                self._actor_calls.pop(spec.task_id.binary(), None)
        self._reply_actor_result_once(conn, spec, results, error_blob)

    def _reply_direct_result(self, conn: Connection, spec: TaskSpec,
                             results, error_blob):
        """Reply for a lease-pushed direct task, coalescing with its
        neighbours: results buffer per owner connection and a tick-bounded
        flusher sends each run as one task_result_batch frame — per-result
        framing + a syscall + an owner-side wakeup each would otherwise
        dominate small-task throughput. A full buffer flushes inline; the
        flush tick bounds how long any result can sit, so a fast result is
        never held hostage by a slow successor task."""
        # Store-path results must be raylet-registered BEFORE the owner
        # learns of them (same ordering as _reply_actor_result).
        for r in results:
            if r["kind"] == "store":
                try:
                    self.raylet.call(
                        "object_sealed",
                        {"object_id": r["object_id"], "size": r["size"],
                         "owner": self.worker_id.hex()}, timeout=30)
                except Exception:
                    logger.exception("failed to register direct result")
        item = {"task_id": spec.task_id, "results": results,
                "error": error_blob}
        batch_max = GLOBAL_CONFIG.direct_result_batch_max
        if batch_max <= 1 or GLOBAL_CONFIG.direct_flush_tick_ms <= 0:
            # Coalescing off: per-result push (the A-B-A inert baseline).
            try:
                conn.push("task_result", item)
            except Exception:
                logger.warning("direct result push failed (caller gone?)")
            return
        flush_now = None
        with self._direct_reply_lock:
            buf = self._direct_reply_buf.setdefault(conn, [])
            buf.append(item)
            if len(buf) >= batch_max:
                flush_now = self._direct_reply_buf.pop(conn)
            elif self._direct_reply_flusher is None:
                self._direct_reply_flusher = threading.Thread(
                    target=self._direct_reply_flush_loop,
                    name="direct-reply-flush", daemon=True)
                self._direct_reply_flusher.start()
        if flush_now is not None:
            self._push_direct_replies(conn, flush_now)
        else:
            self._direct_reply_event.set()

    def _push_direct_replies(self, conn: Connection, batch: list):
        try:
            if len(batch) == 1:
                conn.push("task_result", batch[0])
            else:
                conn.push("task_result_batch", {"batch": batch})
        except Exception:
            logger.warning("direct result push failed (caller gone?)")

    def _direct_reply_flush_loop(self):
        while not self._stopping.is_set():
            if not self._direct_reply_event.wait(timeout=0.5):
                continue
            self._direct_reply_event.clear()
            tick = GLOBAL_CONFIG.direct_flush_tick_ms / 1000.0
            if tick > 0:
                time.sleep(tick)  # coalesce the completion burst
            self._flush_direct_replies()
        self._flush_direct_replies()  # final drain on graceful stop

    def _flush_direct_replies(self):
        with self._direct_reply_lock:
            drained = self._direct_reply_buf
            self._direct_reply_buf = {}
        for conn, batch in drained.items():
            self._push_direct_replies(conn, batch)

    def _reply_actor_result(self, conn: Connection, spec: TaskSpec,
                            results, error_blob):
        # Register large results with the raylet so other nodes can pull them.
        for r in results:
            if r["kind"] == "store":
                try:
                    self.raylet.call("object_sealed",
                                     {"object_id": r["object_id"], "size": r["size"],
                                      "owner": self.worker_id.hex()}, timeout=30)
                except Exception:
                    logger.exception("failed to register actor result")
        try:
            conn.push("task_result",
                      {"task_id": spec.task_id, "results": results, "error": error_blob})
        except Exception:
            logger.warning("actor result push failed (caller gone?)")

    def _graceful_exit(self, conn: Connection, spec: TaskSpec):
        self._reply_actor_result(conn, spec, [], None)
        self._stopping.set()
        # os._exit kills the daemon flushers before their final drains —
        # flush the last tasks' events and buffered results synchronously.
        self._flush_direct_replies()
        self._flush_task_events()
        threading.Thread(target=lambda: (os._exit(0)), daemon=True).start()


def forked_main():
    """Entry for forge-forked workers (core/worker_forge.py): the template
    already paid the module imports, so this only resets per-process state
    the fork duplicated — RNG streams (two forked workers must not draw
    identical randomness from the template's inherited state; framework
    ids reseed themselves via the pid-keyed PRNG in ids._random_bytes)
    and the template's logging handlers (main()'s basicConfig would
    otherwise be a no-op and worker logs would carry the forge's
    formatting) — then runs the normal main. The granted env vars were
    applied by the forge child before this call."""
    import random

    random.seed()  # fresh entropy, not the template's inherited state
    np = sys.modules.get("numpy")
    if np is not None:
        # Legacy global stream (new-style Generators are per-use). Seeded
        # from the just-reseeded stdlib RNG: the no-arg form gathers OS
        # entropy and costs ~30ms per fork — pure spawn-latency tax.
        np.random.seed(random.getrandbits(32))
    root = logging.getLogger()
    for h in root.handlers[:]:
        root.removeHandler(h)
    main()


def main():
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format=(f"%(asctime)s [worker pid={os.getpid()}] "
                "%(levelname)s %(name)s: %(message)s"),
    )
    runtime = WorkerRuntime()
    if os.environ.get("RAY_TPU_RUNTIME_ENV"):
        from ray_tpu.core import runtime_env as renv_mod

        try:
            renv_mod.materialize(runtime.gcs,
                                 os.environ.get("RAY_TPU_SESSION_DIR",
                                                "/tmp"))
        except Exception as e:  # noqa: BLE001 — surface to tasks, below
            # Dying here would crash-loop worker spawns while the queued
            # task waits forever; instead stay registered and fail every
            # dispatched task with a typed setup error (reference:
            # RuntimeEnvSetupError on the task, runtime_env_agent path).
            logging.getLogger(__name__).error(
                "runtime_env setup failed: %s", e)
            runtime._env_setup_error = f"{type(e).__name__}: {e}"
    if GLOBAL_CONFIG.log_to_driver:
        from ray_tpu.core.log_streaming import LogStreamer

        def _current_job():
            spec = runtime.executing_task or runtime.actor_spec
            return spec.job_id.hex() if spec is not None else None

        streamer = LogStreamer(runtime.gcs, runtime.worker_id.hex(),
                               os.getpid(), job_provider=_current_job)
        streamer.install()

    def _term(signum, frame):
        # Drain buffered task events on a SEPARATE thread with a bounded
        # join: the handler runs on the main thread, which may be holding
        # _event_lock (mid-buffer) or the RPC send lock (mid-call) right
        # now — flushing inline would self-deadlock and the worker would
        # never exit.
        t = threading.Thread(target=runtime._flush_task_events, daemon=True)
        t.start()
        t.join(timeout=0.5)
        os._exit(0)

    def _cancel(signum, frame):
        # ray.cancel: raise in the main thread (where normal tasks run),
        # but only if the requested task is STILL the one executing — the
        # worker may have finished it and started another.
        spec = runtime.executing_task
        target = runtime._cancel_task_id
        if spec is not None and target is not None and \
                spec.task_id == target:
            runtime._cancel_task_id = None
            from ray_tpu.exceptions import TaskCancelledError

            raise TaskCancelledError(spec.task_id)

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGUSR1, _cancel)
    if os.environ.get("RAY_TPU_WORKER_STACK_SAMPLING"):
        import faulthandler
        faulthandler.register(
            signal.SIGUSR2,
            file=open(f"/tmp/wstack-{os.getpid()}.txt", "w"))
    # Bind the process-global runtime so user code calling ray_tpu.get/put/
    # remote inside tasks routes through this worker's CoreRuntime.
    import ray_tpu

    ray_tpu._global_runtime = runtime
    runtime.register()
    try:
        runtime.main_loop()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
