"""Worker forge: a per-node forkserver that spawns workers in milliseconds.

The reference hides worker cold starts by prestarting one worker per core
(`worker_pool.h:347`); beyond that target every spawn still pays a full
``exec`` plus the Python import bill (~0.7-1s for the worker module set on
this sandbox, ~2.5s with jax). That bill is the actor-creation bottleneck:
every serve replica is an actor, and replica scale-up serializes behind
interpreter cold starts.

The forge is a **template process**, one per OS process hosting raylets
(shared by in-process fake clusters, since the import cache it exists to
amortize is per-process; on a real deployment that is one per raylet) and
reused across clusters — clients detach on node stop and the template
lingers, self-exiting when its parent dies or no control connection
remains for 30s:

- it preimports the heavy module set (``worker_forge_preimports``, default
  ``ray_tpu.core.worker,numpy``) and then does nothing but watch a unix
  socket — single-threaded, no RPC clients, no XLA backend client;
- on a spawn request it ``fork()``s: the child inherits the warm module
  cache (copy-on-write), applies its granted env vars, redirects stdio to
  its worker log, reseeds per-process RNG state, and only THEN connects to
  the raylet and runs the normal worker main loop;
- it reaps its children via SIGCHLD and streams ``exit`` events back to the
  raylet, so forged-worker death detection is event-driven (no waitpid
  surface exists across the process boundary).

Fork-safety contract (asserted, not assumed): at fork time the template
must have exactly one thread and no initialized XLA backend — a forked
child of a multi-threaded parent can deadlock on locks held by threads
that don't survive the fork, and a forked XLA client would share chip
handles between processes. The template refuses to fork when the contract
is violated (the raylet falls back to cold spawn), and ``status`` exposes
the thread/XLA state so tests can pin it.

Fork-incompatible grants — currently a TPU chip grant
(``RAY_TPU_GRANTED_TPU``), whose sitecustomize plugin hook must run at
interpreter start — always take the cold ``exec`` path.

Wire protocol (length-prefixed msgpack frames over the unix socket):

    -> {c: "spawn", env: {delta vars}, cwd, log}   => {ok, pid | error}
    -> {c: "status"}                               => {ok, pid, threads,
                                                       xla_initialized,
                                                       preimported, ...}
    <- {c: "exit", pid, code}                      (async, broadcast)

Replies are FIFO per connection (the client serializes calls); ``exit``
events interleave and are routed by the client's reader thread.
"""

from __future__ import annotations

import logging
import os
import queue
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import msgpack

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<I")

# Template-side liveness/orphan policy.
_IDLE_EXIT_S = 30.0       # no control connection this long -> exit
_SELECT_TICK_S = 1.0      # ppid / idle / term-flag check cadence


def _send_frame(sock: socket.socket, obj: Dict[str, Any],
                lock: Optional[threading.Lock] = None):
    buf = msgpack.packb(obj)
    data = _HDR.pack(len(buf)) + buf
    if lock is not None:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


def _recv_frame(sock: socket.socket) -> Dict[str, Any]:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("forge peer closed")
        hdr += chunk
    (n,) = _HDR.unpack(hdr)
    body = bytearray()
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            raise ConnectionError("forge peer closed")
        body += chunk
    return msgpack.unpackb(bytes(body))


def process_tag() -> str:
    """Marker carried in the template's argv (and therefore in every
    forked worker's cmdline): identifies the driver/raylet process that
    owns the template, for orphan scans and debugging."""
    return f"rtpuforge-{os.getpid()}"


# --------------------------------------------------------------------------- #
# Template (forge process) side
# --------------------------------------------------------------------------- #


class _ForgeTemplate:
    """The forkserver loop. Runs as ``python -m ray_tpu.core.worker_forge``;
    deliberately single-threaded — see the module fork-safety contract."""

    def __init__(self, socket_path: str, preimports: List[str]):
        self._socket_path = socket_path
        self._preimports = preimports
        self._preimported: List[str] = []
        self._import_errors: Dict[str, str] = {}
        self._children: set = set()
        self._forks = 0
        self._term = False
        self._start_ppid = os.getppid()
        self._conns: List[socket.socket] = []
        self._listener: Optional[socket.socket] = None
        self._wakeup_r = -1
        self._wakeup_w = -1
        self._last_conn_s = time.monotonic()

    # ------------------------------------------------------------ lifecycle

    def run(self) -> int:
        for mod in self._preimports:
            mod = mod.strip()
            if not mod:
                continue
            try:
                __import__(mod)
                self._preimported.append(mod)
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                self._import_errors[mod] = f"{type(e).__name__}: {e}"
                logger.warning("forge preimport of %s failed: %s", mod, e)
        try:
            os.unlink(self._socket_path)
        except OSError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self._socket_path)
        self._listener.listen(8)
        # SIGCHLD wakes the select loop through the wakeup pipe so child
        # exits are reaped (and reported) immediately, not on the next tick.
        self._wakeup_r, self._wakeup_w = os.pipe()
        os.set_blocking(self._wakeup_w, False)
        signal.set_wakeup_fd(self._wakeup_w)
        signal.signal(signal.SIGCHLD, lambda s, f: None)
        signal.signal(signal.SIGTERM, self._on_term)
        logger.info("forge ready on %s (preimported: %s)",
                    self._socket_path, ",".join(self._preimported))
        try:
            self._loop()
        finally:
            self._shutdown()
        return 0

    def _on_term(self, signum, frame):
        self._term = True

    def _shutdown(self):
        # Forward TERM to surviving children: the raylet kills the workers
        # it knows about before stopping the forge, so anything left here
        # is an in-flight spawn that must not outlive the node.
        for pid in list(self._children):
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self._socket_path)
        except OSError:
            pass

    # ----------------------------------------------------------- main loop

    def _loop(self):
        import select

        while not self._term:
            rlist = [self._listener, self._wakeup_r] + self._conns
            try:
                ready, _, _ = select.select(rlist, [], [], _SELECT_TICK_S)
            except InterruptedError:
                ready = []
            except OSError:
                return
            self._reap()
            if self._term:
                return
            if os.getppid() != self._start_ppid:
                logger.info("forge parent died; exiting")
                return
            if not self._conns and \
                    time.monotonic() - self._last_conn_s > _IDLE_EXIT_S:
                logger.info("forge idle with no control connection; exiting")
                return
            for r in ready:
                if r is self._wakeup_r:
                    try:
                        os.read(self._wakeup_r, 4096)
                    except OSError:
                        pass
                elif r is self._listener:
                    try:
                        conn, _ = self._listener.accept()
                        self._conns.append(conn)
                        self._last_conn_s = time.monotonic()
                    except OSError:
                        pass
                else:
                    self._serve_one(r)
            if self._conns:
                self._last_conn_s = time.monotonic()

    def _serve_one(self, conn: socket.socket):
        try:
            req = _recv_frame(conn)
        except (ConnectionError, OSError):
            self._drop_conn(conn)
            return
        cmd = req.get("c")
        try:
            if cmd == "spawn":
                reply = self._handle_spawn(req)
            elif cmd == "status":
                reply = self._status()
            else:
                reply = {"ok": False, "error": f"unknown command {cmd!r}"}
        except Exception as e:  # noqa: BLE001 — reply, don't die
            logger.exception("forge command %s failed", cmd)
            reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        reply["i"] = req.get("i", 0)  # correlation id, echoed verbatim
        try:
            _send_frame(conn, reply)
        except OSError:
            self._drop_conn(conn)

    def _drop_conn(self, conn: socket.socket):
        try:
            conn.close()
        except OSError:
            pass
        if conn in self._conns:
            self._conns.remove(conn)
        self._last_conn_s = time.monotonic()

    def _status(self) -> Dict[str, Any]:
        xla = False
        if "jax" in sys.modules:
            try:
                from jax._src import xla_bridge

                xla = bool(getattr(xla_bridge, "_backends", None))
            except Exception:  # noqa: BLE001 — jax internals moved
                xla = False
        return {"ok": True, "pid": os.getpid(),
                "threads": threading.active_count(),
                "xla_initialized": xla,
                "preimported": list(self._preimported),
                "import_errors": dict(self._import_errors),
                "forks": self._forks,
                "children": len(self._children)}

    def _reap(self):
        while True:
            try:
                pid, status = os.waitpid(-1, os.WNOHANG)
            except ChildProcessError:
                return
            if pid == 0:
                return
            self._children.discard(pid)
            try:
                code = os.waitstatus_to_exitcode(status)
            except ValueError:
                code = -1
            event = {"c": "exit", "pid": pid, "code": code}
            for conn in list(self._conns):
                try:
                    _send_frame(conn, event)
                except OSError:
                    self._drop_conn(conn)

    # ---------------------------------------------------------------- fork

    def _handle_spawn(self, req: Dict[str, Any]) -> Dict[str, Any]:
        # Fork-safety contract: refuse rather than fork a process whose
        # other threads may hold locks the child would inherit frozen.
        if threading.active_count() != 1:
            return {"ok": False,
                    "error": f"template has {threading.active_count()} "
                             "threads; fork is unsafe"}
        st = self._status()
        if st["xla_initialized"]:
            return {"ok": False,
                    "error": "template initialized an XLA backend; "
                             "fork is unsafe"}
        self._reap()  # bound the zombie window even under spawn storms
        pid = os.fork()
        if pid != 0:
            self._forks += 1
            self._children.add(pid)
            return {"ok": True, "pid": pid}
        # ------------------------------------------------------- child
        try:
            self._child_main(req)
        except BaseException:  # noqa: BLE001 — child must never return
            import traceback

            traceback.print_exc()
        finally:
            os._exit(1)

    def _child_main(self, req: Dict[str, Any]):
        # Shed every forge artifact before touching worker state: signal
        # plumbing first (a stray SIGCHLD must not write a closed pipe),
        # then the inherited sockets.
        signal.set_wakeup_fd(-1)
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        self._children.clear()
        for s in [self._listener] + self._conns:
            try:
                s.close()
            except OSError:
                pass
        for fd in (self._wakeup_r, self._wakeup_w):
            try:
                os.close(fd)
            except OSError:
                pass
        log_path = req.get("log")
        if log_path:
            fd = os.open(log_path,
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
            sys.stdout.flush()
            sys.stderr.flush()
            os.dup2(fd, 1)
            os.dup2(fd, 2)
            os.close(fd)
        env = {str(k): str(v) for k, v in (req.get("env") or {}).items()}
        os.environ.update(env)
        cwd = req.get("cwd")
        if cwd:
            try:
                os.chdir(cwd)
            except OSError:
                pass
        # PYTHONPATH landed after interpreter start: graft it onto sys.path
        # so worker-side function/module resolution matches a cold spawn.
        for p in reversed(os.environ.get("PYTHONPATH", "")
                          .split(os.pathsep)):
            if p and p not in sys.path:
                sys.path.insert(0, p)
        from ray_tpu.core import worker

        worker.forked_main()
        os._exit(0)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="ray_tpu.core.worker_forge")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--tag", default="", help="owner-process marker (lands "
                    "in this process's and every forked worker's argv, so "
                    "orphan scans can find them)")
    ap.add_argument("--preimports", default="")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format=(f"%(asctime)s [forge pid={os.getpid()}] "
                "%(levelname)s %(name)s: %(message)s"))
    tmpl = _ForgeTemplate(args.socket, args.preimports.split(","))
    return tmpl.run()


# --------------------------------------------------------------------------- #
# Raylet (client) side
# --------------------------------------------------------------------------- #


class ForgeUnavailable(RuntimeError):
    """The forge cannot serve this spawn (dead, not ready, or refused)."""


class _ForgedProc:
    """Popen-quacking handle for a forge-forked worker.

    The worker is a child of the forge template, not of this process, so
    the Popen surface (poll/wait/terminate/kill) is emulated from forge
    ``exit`` events, falling back to liveness probes once the template
    incarnation that forked the worker is gone (events can no longer
    arrive; the orphaned child gets reparented and reaped by init)."""

    def __init__(self, pid: int, forge: "WorkerForge", generation: int):
        self.pid = pid
        self.returncode: Optional[int] = None
        self._forge = forge
        self._generation = generation
        self._exited = threading.Event()

    def _mark_exited(self, code: int):
        if self.returncode is None:
            self.returncode = code
        self._exited.set()

    def _events_lost(self) -> bool:
        f = self._forge
        return f is None or f.generation != self._generation or not f.alive

    def poll(self) -> Optional[int]:
        if self.returncode is None and self._events_lost():
            try:
                os.kill(self.pid, 0)
            except ProcessLookupError:
                self._mark_exited(-1)
            except PermissionError:
                pass  # exists under another uid: pid recycled, leave None
        return self.returncode

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self.poll() is not None:
                return self.returncode
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise subprocess.TimeoutExpired("forged-worker", timeout)
            # Short slices: the event path resolves instantly; the slice
            # only bounds the probe cadence after a forge death.
            step = 0.2 if remaining is None else min(0.2, remaining)
            if self._exited.wait(step):
                return self.returncode

    def _signal(self, sig: int):
        if self.returncode is not None:
            return
        try:
            os.kill(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def terminate(self):
        self._signal(signal.SIGTERM)

    def kill(self):
        self._signal(signal.SIGKILL)


_CONN_LOST = object()  # reply-queue sentinel: reader died mid-call


class _SharedTemplate:
    """One template PROCESS, shared by every WorkerForge client in this
    process and reused across clusters.

    Why shared: the template's value is its warm import cache, and the
    import bill is per-process — N in-process raylets (cluster_utils fake
    clusters, the bench envelope, the test suite) each paying ~1s of
    template imports per cluster would cost more than cold spawns save.
    One template serves any raylet: every spawn request carries its full
    env delta (raylet/GCS addresses, session, worker id), so the template
    holds no per-cluster state. On a real deployment (one raylet per host
    process) this is exactly one template per raylet, as before.

    Lifetime: lazily (re)launched on demand; never killed on client
    stop — it lingers and self-reaps via its own guards (exits when its
    parent process dies or after 30s with no control connection), so the
    next cluster in a long-lived process reconnects to a warm template
    instead of re-paying the imports. `kill()` exists for a wedged
    template (reply timeout) and for tests."""

    def __init__(self, preimports: str):
        self.preimports = preimports
        self.lock = threading.Lock()
        self.proc: Optional[subprocess.Popen] = None
        self.launching = False
        self._seq = 0
        self.socket_path = ""
        base = os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu")
        self.log_path = os.path.join(base, f"{process_tag()}.log")

    def ensure(self) -> str:
        """Launch the template if it isn't running; returns the socket
        path clients should (re)connect to. The Popen runs OUTSIDE the
        lock (RL002); a concurrent ensure() sees `launching` and just
        returns the new socket path — its connect loop retries until the
        fresh template binds it."""
        with self.lock:
            if (self.proc is not None and self.proc.poll() is None) \
                    or self.launching:
                return self.socket_path
            self.launching = True
            self._seq += 1
            # Proc-scoped /tmp path: short (AF_UNIX 107-byte limit) and
            # independent of any session dir that may be torn down while
            # the template lingers.
            self.socket_path = f"/tmp/{process_tag()}-{self._seq}.sock"
            path = self.socket_path
        proc = None
        try:
            proc = self._launch(path)
        finally:
            with self.lock:
                self.proc = proc
                self.launching = False
        return path

    def _launch(self, socket_path: str) -> subprocess.Popen:
        from ray_tpu.core.config import GLOBAL_CONFIG

        env = dict(os.environ)
        env.update(GLOBAL_CONFIG.to_env())
        # Template mirrors the CPU-worker env (WorkerPool.spawn_worker):
        # the site-level accelerator hook must not fire, and any jax
        # the template (or its children) touches stays on CPU.
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["RAY_TPU_JAX_PLATFORM"] = "cpu"
        import ray_tpu as _pkg

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(_pkg.__file__)))
        parts = [pkg_root, os.getcwd()] + \
            ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        out = open(self.log_path, "ab")
        try:
            return subprocess.Popen(
                [sys.executable, "-u", "-m", "ray_tpu.core.worker_forge",
                 "--socket", socket_path,
                 "--tag", process_tag(),
                 "--preimports", self.preimports],
                env=env, stdout=out, stderr=subprocess.STDOUT,
                cwd=os.getcwd(), close_fds=True)
        finally:
            out.close()

    def kill(self):
        with self.lock:
            proc = self.proc
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.terminate()
            proc.wait(timeout=2.0)
        except subprocess.TimeoutExpired:
            try:
                proc.kill()
                proc.wait(timeout=1.0)
            except (OSError, subprocess.TimeoutExpired):
                pass
        except OSError:
            pass


_templates_lock = threading.Lock()
_templates: Dict[str, _SharedTemplate] = {}


def shared_template(preimports: str) -> _SharedTemplate:
    with _templates_lock:
        t = _templates.get(preimports)
        if t is None:
            t = _templates[preimports] = _SharedTemplate(preimports)
        return t


class WorkerForge:
    """Raylet-side forge lifecycle + spawn client.

    Thread model: ``spawn``/``status`` calls pipeline freely — requests
    carry correlation ids, a reader thread routes each reply to its
    caller's slot (and exit events to the raylet callback), so no caller
    ever blocks while holding a lock. Template (re)starts run on
    background threads. All threads are daemons AND joined on ``stop()``.
    Never call into this class while holding the worker-pool or raylet
    lock — spawn is a socket round trip (RL002).
    """

    # Give up on the forge after this many consecutive template failures
    # (crash-looping template: every spawn would eat a restart attempt).
    MAX_CONSECUTIVE_FAILURES = 5

    def __init__(self, session_dir: str, session_suffix: str,
                 node_hex: str,
                 on_worker_exit: Optional[Callable[[int, int], None]] = None,
                 preimports: Optional[str] = None):
        self._session_dir = session_dir
        self._session_suffix = session_suffix
        self._node_hex = node_hex
        self.on_worker_exit = on_worker_exit
        # Per-runtime-env template override (comma-separated module list):
        # a job whose runtime_env carries `preimports` gets its own forge
        # keyed on this set, so its workers fork with the job's heavy
        # modules already imported. None -> the node-wide default set.
        self._preimports_override = preimports
        self._template: Optional[_SharedTemplate] = None
        self.generation = 0
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._msg_counter = 0
        self._pending: Dict[int, "queue.Queue"] = {}  # msg id -> reply slot
        self._ready = threading.Event()
        self._stopped = threading.Event()
        self._restarting = False
        self._consecutive_failures = 0
        self._procs: Dict[int, _ForgedProc] = {}
        self._early_exits: Dict[int, int] = {}
        self._threads: List[threading.Thread] = []

    # ------------------------------------------------------------ lifecycle

    @property
    def alive(self) -> bool:
        return self._ready.is_set()

    @property
    def proc(self) -> Optional[subprocess.Popen]:
        """The (shared) template process handle."""
        return self._template.proc if self._template is not None else None

    def wait_ready(self, timeout: float = 30.0) -> bool:
        return self._ready.wait(timeout)

    @staticmethod
    def compatible(env_extra: Dict[str, str]) -> bool:
        """Can this grant run in a forked worker? A TPU chip grant needs
        the sitecustomize accelerator hook at interpreter start (and a
        per-process chip lock), so it always cold-spawns."""
        return "RAY_TPU_GRANTED_TPU" not in env_extra

    def start(self):
        """Attach to the process-shared template — launching it if
        needed — and connect in the background (a fresh template pays the
        preimport bill before it binds the socket; spawns before
        readiness fall back to cold). A warm lingering template from an
        earlier cluster in this process connects in milliseconds."""
        from ray_tpu.core.config import GLOBAL_CONFIG

        self._template = shared_template(
            self._preimports_override
            if self._preimports_override is not None
            else GLOBAL_CONFIG.worker_forge_preimports)
        self._launch_template()
        t = threading.Thread(target=self._connect_loop,
                             args=(self.generation,),
                             name="forge-connect", daemon=True)
        t.start()
        self._track(t)

    def _launch_template(self):
        self.generation += 1
        with self._state_lock:
            self._procs.clear()  # stale generation: they self-detect
            self._early_exits.clear()
        self._socket_path = self._template.ensure()

    def _connect_loop(self, generation: int):
        deadline = time.monotonic() + 60.0
        while not self._stopped.is_set() and generation == self.generation:
            proc = self._template.proc
            if not self._template.launching and (
                    proc is None or proc.poll() is not None):
                self._template_failed("template exited during startup")
                return
            try:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.connect(self._socket_path)
            except OSError:
                if time.monotonic() > deadline:
                    self._template_failed("template never became ready")
                    return
                time.sleep(0.05)
                continue
            if self._stopped.is_set() or generation != self.generation:
                # Lost the race with stop()/restart: this socket belongs
                # to nobody — close it rather than leak the fd.
                try:
                    sock.close()
                except OSError:
                    pass
                return
            self._sock = sock
            self._consecutive_failures = 0
            self._ready.set()
            t = threading.Thread(target=self._read_loop,
                                 args=(sock, generation),
                                 name="forge-reader", daemon=True)
            t.start()
            self._track(t)
            return

    def _template_failed(self, reason: str):
        logger.warning("worker forge: %s (cold spawns continue)", reason)
        self._consecutive_failures += 1
        self._mark_dead()
        if self._consecutive_failures < self.MAX_CONSECUTIVE_FAILURES:
            self.restart_async()
        else:
            logger.error(
                "worker forge disabled after %d consecutive failures — "
                "see %s", self._consecutive_failures,
                self._template.log_path if self._template else "?")

    def _mark_dead(self):
        self._ready.clear()
        sock, self._sock = self._sock, None
        if sock is not None:
            # shutdown() BEFORE close: close() alone does not wake a
            # reader blocked in recv() on a healthy connection (the
            # lingering shared template keeps its end open), and stop()
            # would then burn its full join timeout per forge client.
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        # Unblock every call parked on a reply slot.
        with self._state_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for slot in pending:
            slot.put(_CONN_LOST)

    def _read_loop(self, sock: socket.socket, generation: int):
        try:
            while not self._stopped.is_set():
                frame = _recv_frame(sock)
                if frame.get("c") == "exit":
                    pid, code = frame["pid"], frame["code"]
                    with self._state_lock:
                        proc = self._procs.pop(pid, None)
                        if proc is None:
                            # Exit raced the spawn reply: stash for the
                            # spawn() caller to consume on registration.
                            # Bounded: exit events broadcast to EVERY
                            # client of the shared template, so most pids
                            # here belong to other raylets' workers and
                            # no spawn() of ours will ever claim them.
                            self._early_exits[pid] = code
                            while len(self._early_exits) > 256:
                                self._early_exits.pop(
                                    next(iter(self._early_exits)))
                    if proc is not None:
                        proc._mark_exited(code)
                    cb = self.on_worker_exit
                    if cb is not None and proc is not None:
                        try:
                            cb(pid, code)
                        except Exception:  # noqa: BLE001 — observer only
                            logger.exception("forge exit callback failed")
                else:
                    with self._state_lock:
                        slot = self._pending.pop(frame.get("i", 0), None)
                    if slot is not None:
                        slot.put(frame)
        except (ConnectionError, OSError):
            pass
        finally:
            if not self._stopped.is_set() and generation == self.generation:
                self._template_failed("control connection lost")

    def restart_async(self):
        """Relaunch a dead template in the background (spawns keep falling
        back to cold until the new one is ready)."""
        with self._state_lock:
            if (self._stopped.is_set() or self._ready.is_set()
                    or self._restarting
                    or self._consecutive_failures
                    >= self.MAX_CONSECUTIVE_FAILURES):
                return
            self._restarting = True
        t = threading.Thread(target=self._restart, name="forge-restart",
                             daemon=True)
        t.start()
        self._track(t)

    def _restart(self):
        try:
            while (not self._stopped.is_set() and not self._ready.is_set()
                   and self._consecutive_failures
                   < self.MAX_CONSECUTIVE_FAILURES):
                # Settle delay: lets a dying template release its socket
                # and spaces out attempts when the template crash-loops.
                backoff = min(5.0, 0.5 * (2 ** self._consecutive_failures))
                if self._stopped.wait(backoff):
                    return
                if self._consecutive_failures >= 2:
                    # Repeated failures against a live process: the shared
                    # template is wedged, not merely our connection —
                    # escalate to a kill + respawn. A single failure only
                    # reconnects (the template serves other raylets too).
                    self._template.kill()
                self._launch_template()
                self._connect_loop(self.generation)
        finally:
            with self._state_lock:
                self._restarting = False

    def stop(self):
        """Detach from the shared template (which lingers for the next
        cluster in this process and self-exits on idle or parent death —
        never killed here: other raylets may still be using it)."""
        self._stopped.set()
        self._mark_dead()
        with self._state_lock:
            threads = list(self._threads)
            self._threads.clear()
        for t in threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)

    def _track(self, t: threading.Thread):
        with self._state_lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    # ----------------------------------------------------------------- RPC

    def _call(self, req: Dict[str, Any],
              timeout: float = 10.0) -> Dict[str, Any]:
        sock = self._sock
        if sock is None or not self._ready.is_set():
            raise ForgeUnavailable("forge is not running")
        slot: "queue.Queue" = queue.Queue()
        with self._state_lock:
            self._msg_counter += 1
            msg_id = self._msg_counter
            self._pending[msg_id] = slot
        req = dict(req, i=msg_id)
        try:
            _send_frame(sock, req, self._send_lock)
        except OSError as e:
            with self._state_lock:
                self._pending.pop(msg_id, None)
            self._mark_dead()
            raise ForgeUnavailable(f"forge send failed: {e}")
        try:
            reply = slot.get(timeout=timeout)
        except queue.Empty:
            with self._state_lock:
                self._pending.pop(msg_id, None)
            # A wedged template can't be trusted with the next fork.
            self._mark_dead()
            raise ForgeUnavailable("forge reply timed out")
        if reply is _CONN_LOST:
            raise ForgeUnavailable("forge died mid-call")
        if not reply.get("ok"):
            raise ForgeUnavailable(reply.get("error", "forge refused"))
        return reply

    def spawn(self, env_delta: Dict[str, str], cwd: str,
              log_path: str) -> _ForgedProc:
        """Fork a fully-imported worker; returns its Popen-like handle.
        Raises ForgeUnavailable (caller falls back to cold spawn)."""
        from ray_tpu.observability import tracing as _tracing

        with _tracing.get_tracer().start_span("forge.fork") as span:
            reply = self._call({"c": "spawn", "env": env_delta, "cwd": cwd,
                                "log": log_path})
            span.set_attr("pid", reply.get("pid"))
        pid = reply["pid"]
        proc = _ForgedProc(pid, self, self.generation)
        with self._state_lock:
            early = self._early_exits.pop(pid, None)
            if early is None:
                self._procs[pid] = proc
        if early is not None:
            proc._mark_exited(early)
        return proc

    def status(self) -> Dict[str, Any]:
        """Template introspection (fork-safety tests, debug_state)."""
        return self._call({"c": "status"})


if __name__ == "__main__":
    sys.exit(main())
