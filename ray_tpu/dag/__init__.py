"""Lazy task DAGs: build with `.bind()`, run with `.execute()`.

Equivalent of the reference's DAG API (`python/ray/dag/`): `fn.bind(...)`
returns a node instead of submitting; nodes compose into a DAG whose
`execute()` submits every task with its dependencies wired as ObjectRefs
(so the scheduler sees the whole graph's edges, and shared subtrees run
once). `InputNode` parameterizes a DAG for repeated execution.

    with InputNode() as x:
        dag = postprocess.bind(model.bind(x))
    out = ray_tpu.get(dag.execute(batch))
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["DAGNode", "FunctionNode", "InputNode", "InputAttributeNode"]


class DAGNode:
    """Base: a lazily-bound computation with upstream DAGNode args."""

    def execute(self, *input_args, **input_kwargs):
        """Submit the whole DAG; returns the ObjectRef of this node's
        result. Shared nodes are submitted exactly once per execute."""
        cache: Dict[int, Any] = {}
        return self._resolve(cache, input_args, input_kwargs)

    def _resolve(self, cache, input_args, input_kwargs):
        raise NotImplementedError

    @staticmethod
    def _resolve_arg(arg, cache, input_args, input_kwargs):
        if isinstance(arg, DAGNode):
            return arg._resolve(cache, input_args, input_kwargs)
        if isinstance(arg, (list, tuple)):
            return type(arg)(
                DAGNode._resolve_arg(a, cache, input_args, input_kwargs)
                for a in arg)
        if isinstance(arg, dict):
            return {k: DAGNode._resolve_arg(v, cache, input_args,
                                            input_kwargs)
                    for k, v in arg.items()}
        return arg


class FunctionNode(DAGNode):
    """`remote_fn.bind(...)`: one task in the DAG."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict,
                 options: Optional[Dict] = None):
        self._fn = remote_fn
        self._args = args
        self._kwargs = kwargs
        self._options = options or {}

    def options(self, **opts) -> "FunctionNode":
        return FunctionNode(self._fn, self._args, self._kwargs,
                            {**self._options, **opts})

    def _resolve(self, cache, input_args, input_kwargs):
        key = id(self)
        if key in cache:
            return cache[key]
        args = [self._resolve_arg(a, cache, input_args, input_kwargs)
                for a in self._args]
        kwargs = {k: self._resolve_arg(v, cache, input_args, input_kwargs)
                  for k, v in self._kwargs.items()}
        fn = self._fn.options(**self._options) if self._options else self._fn
        ref = fn.remote(*args, **kwargs)
        cache[key] = ref
        return ref

    # -- introspection (used by workflow's deterministic step ids) -------- #

    def _children(self) -> List["DAGNode"]:
        out: List[DAGNode] = []

        def walk(a):
            if isinstance(a, DAGNode):
                out.append(a)
            elif isinstance(a, (list, tuple)):
                for x in a:
                    walk(x)
            elif isinstance(a, dict):
                for x in a.values():
                    walk(x)

        for a in self._args:
            walk(a)
        for a in self._kwargs.values():
            walk(a)
        return out

    @property
    def name(self) -> str:
        fn = getattr(self._fn, "_function", None)
        return getattr(fn, "__name__", "task")

    def __repr__(self):
        return f"FunctionNode({self.name})"


class InputNode(DAGNode):
    """Placeholder for execute()-time arguments (reference
    `ray.dag.InputNode`); supports `with InputNode() as x:` and
    attribute/index access for multi-field inputs."""

    _local = threading.local()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _resolve(self, cache, input_args, input_kwargs):
        if not input_args and not input_kwargs:
            raise ValueError("DAG has an InputNode: execute() needs arguments")
        if len(input_args) == 1 and not input_kwargs:
            return input_args[0]
        return (input_args, input_kwargs)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return InputAttributeNode(self, name, kind="attr")

    def __getitem__(self, key):
        return InputAttributeNode(self, key, kind="item")


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key, kind: str):
        self._parent = parent
        self._key = key
        self._kind = kind

    def _resolve(self, cache, input_args, input_kwargs):
        if self._kind == "item" and isinstance(self._key, int) \
                and not input_kwargs:
            return input_args[self._key]
        if self._key in input_kwargs:
            return input_kwargs[self._key]
        base = self._parent._resolve(cache, input_args, input_kwargs)
        return getattr(base, self._key) if self._kind == "attr" \
            else base[self._key]
