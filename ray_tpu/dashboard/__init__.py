"""Dashboard: HTTP visibility into the cluster.

Equivalent of the reference's dashboard head (`dashboard/head.py:71`)
reduced to its API surface: JSON state routes + Prometheus metrics + a
single-page HTML overview, served by a thread on the head node. The heavy
React frontend is out of scope by design — the routes carry the same
information.

Routes:
    /                  HTML overview (nodes, actors, jobs, resources)
    /metrics           Prometheus text exposition (aggregated cluster-wide)
    /api/nodes         node table
    /api/actors        actor table
    /api/jobs          driver jobs + submitted jobs
    /api/cluster_resources   totals/availability
"""

from ray_tpu.dashboard.dashboard import DashboardServer

__all__ = ["DashboardServer"]
