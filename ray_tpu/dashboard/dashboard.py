"""Threaded HTTP server exposing cluster state (reference dashboard/head.py)."""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_tpu.core.rpc import RpcClient

logger = logging.getLogger(__name__)

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>body{{font-family:monospace;margin:2em}}table{{border-collapse:collapse}}
td,th{{border:1px solid #999;padding:4px 8px;text-align:left}}</style></head>
<body><h2>ray_tpu cluster</h2>
<h3>Resources</h3><pre>{resources}</pre>
<h3>Nodes</h3>{nodes}
<h3>Actors</h3>{actors}
<h3>Jobs</h3>{jobs}
<p><a href="/metrics">/metrics</a> · <a href="/api/nodes">/api/nodes</a> ·
<a href="/api/actors">/api/actors</a> · <a href="/api/jobs">/api/jobs</a> ·
<a href="/api/timeline">/api/timeline</a></p>
</body></html>"""


def _table(rows, cols):
    import html

    if not rows:
        return "<p>(none)</p>"
    head = "".join(f"<th>{html.escape(str(c))}</th>" for c in cols)
    # Values are cluster-supplied strings (entrypoints, actor names):
    # escape so a hostile name can't script the dashboard page.
    body = "".join(
        "<tr>" + "".join(f"<td>{html.escape(str(r.get(c, '')))}</td>"
                         for c in cols) + "</tr>"
        for r in rows)
    return f"<table><tr>{head}</tr>{body}</table>"


class DashboardServer:
    def __init__(self, gcs_address: str, host: str = "127.0.0.1",
                 port: int = 0):
        self._gcs_address = gcs_address
        self._gcs: Optional[RpcClient] = None
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # stay off stderr
                logger.debug("dashboard: " + fmt, *args)

            def do_GET(self):
                try:
                    dashboard._route(self)
                except Exception as e:  # noqa: BLE001
                    try:
                        self.send_error(500, str(e))
                    except Exception:  # noqa: BLE001 — client gone
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dashboard", daemon=True)

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "DashboardServer":
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._gcs is not None:
            self._gcs.close()

    def _client(self) -> RpcClient:
        if self._gcs is None or self._gcs.is_closed:
            self._gcs = RpcClient(self._gcs_address, name="dashboard->gcs")
        return self._gcs

    # -------------------------------------------------------------- routes

    _PAGE_CALL_TIMEOUT_S = 5.0

    def _gather(self, gcs, methods):
        """Fan the page's GCS calls out in parallel, each with its own
        timeout — one slow/stuck table must not make `/` hang forever or
        serialize four round trips. Failures degrade to empty sections."""
        from concurrent.futures import ThreadPoolExecutor

        def one(method):
            try:
                # RpcClient multiplexes message ids, so concurrent calls
                # share the one GCS connection safely.
                return gcs.call(method, timeout=self._PAGE_CALL_TIMEOUT_S)
            except Exception as e:  # noqa: BLE001 — render what we have
                logger.warning("dashboard: %s failed: %s", method, e)
                return None
        with ThreadPoolExecutor(max_workers=len(methods)) as pool:
            return list(pool.map(one, methods))

    def _route(self, req: BaseHTTPRequestHandler):
        from urllib.parse import parse_qs, urlsplit

        parts = urlsplit(req.path)
        path = parts.path.rstrip("/") or "/"
        query = parse_qs(parts.query)
        gcs = self._client()
        if path == "/":
            import html

            nodes, actors, jobs, subs, res = self._gather(
                gcs, ["get_nodes", "get_actors", "get_jobs", "list_jobs",
                      "cluster_resources"])
            page = _PAGE.format(
                resources=html.escape(
                    json.dumps(res, indent=2, default=str)),
                nodes=_table(nodes or [], ["NodeID", "Alive",
                                           "RayletAddress"]),
                actors=_table(actors or [], ["ActorID", "ClassName",
                                             "State", "Name"]),
                jobs=_table((jobs or []) + (subs or []),
                            ["JobID", "submission_id", "State",
                             "status", "Entrypoint", "entrypoint"]))
            self._send(req, 200, page.encode(), "text/html")
        elif path == "/metrics":
            text = gcs.call("metrics_prometheus")["text"]
            self._send(req, 200, text.encode(),
                       "text/plain; version=0.0.4")
        elif path == "/api/metrics":
            # Same series as /metrics, structured: the programmatic twin
            # of the Prometheus text surface.
            self._json(req, gcs.call("metrics_snapshot"))
        elif path == "/api/nodes":
            self._json(req, gcs.call("get_nodes"))
        elif path == "/api/actors":
            self._json(req, gcs.call("get_actors"))
        elif path == "/api/jobs":
            self._json(req, {"driver_jobs": gcs.call("get_jobs"),
                             "submissions": gcs.call("list_jobs")})
        elif path.startswith("/api/jobs/"):
            # /api/jobs/<sid> -> status record; /api/jobs/<sid>/logs ->
            # the retained log tail (job_log_tail_bytes budget).
            rest = path[len("/api/jobs/"):]
            sid, _, tail = rest.partition("/")
            if tail == "logs":
                resp = gcs.call("job_logs", {"submission_id": sid})
                if not resp.get("found"):
                    self._send(req, 404, b"no such job", "text/plain")
                else:
                    self._send(req, 200, resp["logs"].encode(),
                               "text/plain")
            elif not tail:
                resp = gcs.call("job_info", {"submission_id": sid})
                if not resp.get("found"):
                    self._send(req, 404, b"no such job", "text/plain")
                else:
                    self._json(req, resp["details"])
            else:
                self._send(req, 404, b"not found", "text/plain")
        elif path == "/api/cluster_resources":
            self._json(req, gcs.call("cluster_resources"))
        elif path.startswith("/api/traces/"):
            from ray_tpu.observability import span_tree

            trace_id = path[len("/api/traces/"):]
            resp = gcs.call("trace_get", {"trace_id": trace_id})
            self._json(req, span_tree(resp.get("spans") or [], trace_id))
        elif path == "/api/timeline":
            from ray_tpu.observability import chrome_trace_events

            # ?window=SECONDS and ?limit=N cap the export server-side so
            # a huge trace buffer cannot OOM the JSON encoder.
            window = query.get("window", [None])[0]
            limit = query.get("limit", [None])[0]
            resp = gcs.call("trace_timeline", {
                "window_s": float(window) if window else None,
                "limit": int(limit) if limit else self._TIMELINE_MAX_SPANS})
            out = chrome_trace_events(resp.get("spans") or [])
            out["spanDropCount"] = resp.get("dropped", 0)
            out["spanTruncated"] = resp.get("truncated", 0)
            self._json(req, out)
        else:
            self._send(req, 404, b"not found", "text/plain")

    # Default span cap for /api/timeline when no ?limit= is given.
    _TIMELINE_MAX_SPANS = 20000

    @staticmethod
    def _send(req, code: int, body: bytes, ctype: str):
        req.send_response(code)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def _json(self, req, obj):
        self._send(req, 200, json.dumps(obj, default=str).encode(),
                   "application/json")
