"""ray_tpu.data: distributed datasets executed as tasks over the core.

Equivalent of Ray Data (`python/ray/data/read_api.py`, `dataset.py`):
creation APIs here, transforms/consumption on `Dataset`. Reads are lazy —
each file/chunk becomes a read task fused with downstream transforms.
"""

from __future__ import annotations

import math
import os
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor, BlockMetadata
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import ActorPoolStrategy, Dataset
from ray_tpu.data.iterator import DataIterator, StreamSplitDataIterator
from ray_tpu.data.streaming import (BlockLineage, ByteBudget,
                                    ShardIterator)
from ray_tpu.data import datasource as _ds


def _auto_parallelism(n_items: int) -> int:
    ctx = DataContext.get_current()
    if ctx.read_parallelism > 0:
        return min(n_items, ctx.read_parallelism)
    try:
        import ray_tpu

        cpus = int(ray_tpu.cluster_resources().get("CPU", 2))
    except Exception:
        cpus = 2
    return max(1, min(n_items, 2 * cpus, 192))


# ------------------------------------------------------------------ creation #


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    import builtins

    p = parallelism if parallelism > 0 else _auto_parallelism(max(1, n // 1000))
    per = max(1, -(-n // p))
    work = [(_ds.make_range_block, (s, min(s + per, n)))
            for s in builtins.range(0, n, per)]
    return Dataset(work)


def range_tensor(n: int, *, shape=(1,), parallelism: int = -1) -> Dataset:
    import builtins

    p = parallelism if parallelism > 0 else _auto_parallelism(max(1, n // 1000))
    per = max(1, -(-n // p))
    work = [(_ds.make_tensor_range_block, (s, min(s + per, n), tuple(shape)))
            for s in builtins.range(0, n, per)]
    return Dataset(work)


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    import builtins

    p = parallelism if parallelism > 0 else _auto_parallelism(
        max(1, len(items) // 100))
    per = max(1, -(-len(items) // p)) if items else 1
    work = [(None, (items[s:s + per],))
            for s in builtins.range(0, max(len(items), 1), per)]
    return Dataset(work)


def from_numpy(arr: np.ndarray, *, column: str = "data",
               parallelism: int = -1) -> Dataset:
    import builtins

    n = len(arr)
    p = parallelism if parallelism > 0 else _auto_parallelism(max(1, n // 1000))
    per = max(1, -(-n // p))
    work = [(None, ({column: arr[s:s + per]},))
            for s in builtins.range(0, n, per)]
    return Dataset(work)


def from_pandas(df) -> Dataset:
    return Dataset([(None, (df,))])


def from_arrow(table) -> Dataset:
    return Dataset([(None, (table,))])


# -------------------------------------------------------------------- reads #

Partitioning = _ds.Partitioning


def _file_work(paths, reader, *reader_args,
               partitioning: Optional["Partitioning"] = None,
               partition_filter=None):
    """Shared file-read planning: expand paths, apply the partition
    filter (on parsed partition dicts when a scheme is given, else on
    raw paths), and wrap the reader to attach partition columns
    (reference `file_based_datasource.py` + `partitioning.py`)."""
    import functools

    files = _ds.expand_paths(paths)
    if (partitioning is not None and partitioning.base_dir is None
            and isinstance(paths, str) and os.path.isdir(paths)):
        # Scope parsing to the read root: an ancestor directory that
        # happens to contain '=' (".../run=3/tbl/...") must not leak in
        # as a partition column.
        partitioning = _ds.Partitioning(
            partitioning.style, base_dir=paths,
            field_names=partitioning.field_names or None)
    if partition_filter is not None:
        if partitioning is not None:
            files = [f for f in files
                     if partition_filter(partitioning.parse(f))]
        else:
            files = [f for f in files if partition_filter(f)]
        if not files:
            raise FileNotFoundError(
                "partition_filter excluded every input file")
    if partitioning is not None:
        reader = functools.partial(_ds.partitioned_reader, reader)
        return [(reader, (f, partitioning) + reader_args) for f in files]
    return [(reader, (f,) + reader_args) for f in files]


def read_parquet(paths, *, columns: Optional[List[str]] = None,
                 partitioning: Optional["Partitioning"] = None,
                 partition_filter=None,
                 parallelism: int = -1) -> Dataset:
    return Dataset(_file_work(paths, _ds.read_parquet_file, columns,
                              partitioning=partitioning,
                              partition_filter=partition_filter))


def read_csv(paths, *, partitioning: Optional["Partitioning"] = None,
             partition_filter=None, parallelism: int = -1, **kw) -> Dataset:
    import functools

    reader = functools.partial(_ds.read_csv_file, **kw) if kw \
        else _ds.read_csv_file
    return Dataset(_file_work(paths, reader,
                              partitioning=partitioning,
                              partition_filter=partition_filter))


def read_json(paths, *, lines: bool = True,
              partitioning: Optional["Partitioning"] = None,
              partition_filter=None, parallelism: int = -1) -> Dataset:
    return Dataset(_file_work(paths, _ds.read_json_file, lines,
                              partitioning=partitioning,
                              partition_filter=partition_filter))


def read_text(paths, *, encoding: str = "utf-8",
              parallelism: int = -1) -> Dataset:
    files = _ds.expand_paths(paths)
    return Dataset([(_ds.read_text_file, (f, encoding)) for f in files])


def read_numpy(paths, *, parallelism: int = -1) -> Dataset:
    files = _ds.expand_paths(paths)
    return Dataset([(_ds.read_numpy_file, (f,)) for f in files])


def read_binary_files(paths, *, include_paths: bool = False,
                      parallelism: int = -1) -> Dataset:
    files = _ds.expand_paths(paths)
    return Dataset([(_ds.read_binary_file, (f, include_paths)) for f in files])


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    """Raw TFRecord payloads as {"data": bytes} rows (framing + crc32c
    validated; no TensorFlow dependency)."""
    files = _ds.expand_paths(paths)
    return Dataset([(_ds.read_tfrecord_file, (f,)) for f in files])


def read_sql(sql: str, connection_factory, *, parallelism: int = -1
             ) -> Dataset:
    """One read task running `sql` through a DB-API connection factory
    (reference `ray.data.read_sql`)."""
    return Dataset([(_ds.read_sql_query, (sql, connection_factory))])


def read_images(paths, *, size=None, mode: Optional[str] = None,
                partitioning: Optional["Partitioning"] = None,
                partition_filter=None, parallelism: int = -1) -> Dataset:
    """Decoded images as {"image": ndarray, "path": str} rows."""
    return Dataset(_file_work(paths, _ds.read_image_file, size, mode,
                              partitioning=partitioning,
                              partition_filter=partition_filter))


def read_webdataset(paths, *, decode: bool = True,
                    parallelism: int = -1) -> Dataset:
    """WebDataset tar shards -> sample rows grouped by basename stem
    ({'__key__': ..., '<ext>': value}); one block per shard (reference
    `ray.data.read_webdataset`, standard tarfile — no webdataset dep)."""
    files = _ds.expand_paths(paths)
    return Dataset([(_ds.read_webdataset_shard, (f, decode))
                    for f in files])


def read_mongo(uri: str, database: str, collection: str, *,
               pipeline=None, parallelism: int = -1) -> Dataset:
    """MongoDB collection rows (reference `ray.data.read_mongo`); needs
    pymongo at execution time."""
    return Dataset([(_ds.read_mongo_collection,
                     (uri, database, collection, pipeline))])


__all__ = [
    "ActorPoolStrategy", "Dataset", "DataIterator",
    "StreamSplitDataIterator", "DataContext",
    "BlockLineage", "ByteBudget", "ShardIterator",
    "Block", "BlockAccessor", "BlockMetadata",
    "range", "range_tensor", "from_items", "from_numpy", "from_pandas",
    "from_arrow", "read_parquet", "read_csv", "read_json", "read_text",
    "read_numpy", "read_binary_files", "read_tfrecords", "read_sql",
    "read_images", "read_webdataset", "read_mongo", "Partitioning",
]
