"""Block model: the unit of distributed data.

Equivalent of the reference's block layer (`python/ray/data/block.py`,
`_internal/{arrow_block,pandas_block}.py`) collapsed into one accessor.
A block travels through the object store and is one of:

  - list of rows (simple block)
  - dict[str, np.ndarray] (column batch — the TPU-friendly format: feeds
    jax.device_put without conversion)
  - pandas.DataFrame
  - pyarrow.Table

The accessor normalizes between representations; batches handed to
`map_batches`/`iter_batches` default to the numpy-dict format.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

Block = Any  # list | dict[str, np.ndarray] | pd.DataFrame | pa.Table


@dataclass
class BlockMetadata:
    num_rows: int
    size_bytes: int
    schema: Optional[Any] = None
    input_files: Optional[List[str]] = None


def _is_batch_dict(block: Any) -> bool:
    return isinstance(block, dict) and all(
        isinstance(v, np.ndarray) for v in block.values())


class BlockAccessor:
    def __init__(self, block: Block):
        self._block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # ------------------------------------------------------------- properties

    def num_rows(self) -> int:
        b = self._block
        if isinstance(b, list):
            return len(b)
        if _is_batch_dict(b):
            return len(next(iter(b.values()))) if b else 0
        try:
            import pyarrow as pa

            if isinstance(b, pa.Table):
                return b.num_rows
        except ImportError:
            pass
        if hasattr(b, "shape"):  # DataFrame / ndarray
            return int(b.shape[0])
        raise TypeError(f"unknown block type {type(b)}")

    def size_bytes(self) -> int:
        b = self._block
        if isinstance(b, list):
            import sys

            return sum(sys.getsizeof(r) for r in b[:100]) * max(1, len(b) // 100) \
                if b else 0
        if _is_batch_dict(b):
            return sum(v.nbytes for v in b.values())
        try:
            import pyarrow as pa

            if isinstance(b, pa.Table):
                return b.nbytes
        except ImportError:
            pass
        if hasattr(b, "memory_usage"):
            return int(b.memory_usage(deep=True).sum())
        if hasattr(b, "nbytes"):
            return int(b.nbytes)
        return 0

    def schema(self) -> Any:
        b = self._block
        if isinstance(b, list):
            return type(b[0]).__name__ if b else None
        if _is_batch_dict(b):
            return {k: str(v.dtype) for k, v in b.items()}
        try:
            import pyarrow as pa

            if isinstance(b, pa.Table):
                return b.schema
        except ImportError:
            pass
        if hasattr(b, "dtypes"):
            return dict(b.dtypes.astype(str))
        return None

    def metadata(self, input_files: Optional[List[str]] = None) -> BlockMetadata:
        return BlockMetadata(self.num_rows(), self.size_bytes(), self.schema(),
                             input_files)

    # ------------------------------------------------------------ conversions

    def rows(self) -> Iterator[Any]:
        b = self._block
        if isinstance(b, list):
            yield from b
        elif _is_batch_dict(b):
            keys = list(b)
            for i in range(self.num_rows()):
                yield {k: b[k][i] for k in keys}
        else:
            df = self.to_pandas()
            for _, row in df.iterrows():
                yield row.to_dict()

    def to_batch(self) -> Dict[str, np.ndarray]:
        """Numpy-dict view (the default batch format)."""
        b = self._block
        if _is_batch_dict(b):
            return b
        if isinstance(b, list):
            if b and isinstance(b[0], dict):
                keys = list(b[0])
                return {k: np.asarray([r[k] for r in b]) for k in keys}
            return {"item": np.asarray(b)}
        try:
            import pyarrow as pa

            if isinstance(b, pa.Table):
                return {name: b.column(name).to_numpy(zero_copy_only=False)
                        for name in b.column_names}
        except ImportError:
            pass
        if hasattr(b, "columns"):  # DataFrame
            return {c: b[c].to_numpy() for c in b.columns}
        raise TypeError(f"cannot batch block of type {type(b)}")

    def to_pandas(self):
        import pandas as pd

        b = self._block
        if hasattr(b, "columns") and hasattr(b, "dtypes"):
            return b
        try:
            import pyarrow as pa

            if isinstance(b, pa.Table):
                return b.to_pandas()
        except ImportError:
            pass
        if _is_batch_dict(b):
            return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                                 for k, v in b.items()})
        if isinstance(b, list):
            if b and isinstance(b[0], dict):
                return pd.DataFrame(b)
            return pd.DataFrame({"item": b})
        raise TypeError(f"cannot convert block of type {type(b)}")

    def to_arrow(self):
        import pyarrow as pa

        b = self._block
        if isinstance(b, pa.Table):
            return b
        return pa.Table.from_pandas(self.to_pandas())

    # ------------------------------------------------------------- operations

    def slice(self, start: int, end: int) -> Block:
        b = self._block
        if isinstance(b, list):
            return b[start:end]
        if _is_batch_dict(b):
            return {k: v[start:end] for k, v in b.items()}
        try:
            import pyarrow as pa

            if isinstance(b, pa.Table):
                return b.slice(start, end - start)
        except ImportError:
            pass
        return b.iloc[start:end]

    def take(self, n: int) -> List[Any]:
        out = []
        for row in self.rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if BlockAccessor(b).num_rows() > 0] or blocks[:1]
        if not blocks:
            return []
        first = blocks[0]
        if isinstance(first, list):
            out: List[Any] = []
            for b in blocks:
                out.extend(b if isinstance(b, list)
                           else BlockAccessor(b).take(BlockAccessor(b).num_rows()))
            return out
        if _is_batch_dict(first):
            keys = list(first)
            return {k: np.concatenate([BlockAccessor(b).to_batch()[k]
                                       for b in blocks]) for k in keys}
        try:
            import pyarrow as pa

            if isinstance(first, pa.Table):
                return pa.concat_tables([BlockAccessor(b).to_arrow()
                                         for b in blocks])
        except ImportError:
            pass
        import pandas as pd

        return pd.concat([BlockAccessor(b).to_pandas() for b in blocks],
                         ignore_index=True)

    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """Normalize a user map_batches return value into a block."""
        if batch is None:
            return []
        if _is_batch_dict(batch) or isinstance(batch, list):
            return batch
        if isinstance(batch, dict):
            return {k: np.asarray(v) for k, v in batch.items()}
        if isinstance(batch, np.ndarray):
            return {"item": batch}
        return batch  # DataFrame / Table pass through
