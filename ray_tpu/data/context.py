"""DataContext: execution knobs (reference `python/ray/data/context.py:134`)."""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional


@dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # Streaming executor backpressure: max concurrent tasks per operator.
    # (Buffered OUTPUT is bounded in bytes, not blocks — see
    # `inflight_budget_bytes` below and ray_tpu/data/streaming/budget.py.)
    max_tasks_in_flight_per_op: int = 8
    # Legacy secondary cap on buffered blocks per op; the byte budget is
    # the primary backpressure signal since the streaming ingest plane.
    max_buffered_blocks_per_op: int = 16
    read_parallelism: int = -1  # -1 = auto (min(files, 2*CPUs, 192))
    eager_free: bool = True
    # Per-operator wall/rows stats (ds.stats()); one fire-and-forget
    # actor call per executed block when enabled.
    enable_stats: bool = True

    # Byte-budget knobs are PROMOTED into core/config.py (env-overridable
    # `RAY_TPU_DATA_*`, refresh()-aware memoized reads): `None` here means
    # "consult GLOBAL_CONFIG on every resolve", so an env var set before
    # ray_tpu.init() takes effect without touching the context; assigning
    # a value is an explicit per-process override that always wins.
    inflight_budget_bytes: Optional[int] = None
    prefetch_shards: Optional[int] = None
    locality_routing: Optional[bool] = None
    sort_sample_rows: Optional[int] = None
    broadcast_join_bytes: Optional[int] = None
    # Tenant the data plane charges this process's executions to (the
    # per-tenant budget ledger in streaming/budget.py). None resolves to
    # the submitting job id (RAY_TPU_JOB_ID) and finally "default".
    tenant: Optional[str] = None

    def resolved_inflight_budget_bytes(self) -> int:
        """0 = negotiate against the object store (ByteBudget.negotiated)."""
        if self.inflight_budget_bytes is not None:
            return self.inflight_budget_bytes
        from ray_tpu.core.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG.data_inflight_budget_bytes

    def resolved_prefetch_shards(self) -> int:
        if self.prefetch_shards is not None:
            return self.prefetch_shards
        from ray_tpu.core.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG.data_prefetch_shards

    def resolved_locality_routing(self) -> bool:
        if self.locality_routing is not None:
            return self.locality_routing
        from ray_tpu.core.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG.data_locality_routing

    def resolved_sort_sample_rows(self) -> int:
        if self.sort_sample_rows is not None:
            return self.sort_sample_rows
        from ray_tpu.core.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG.query_sort_sample_rows

    def resolved_broadcast_join_bytes(self) -> int:
        if self.broadcast_join_bytes is not None:
            return self.broadcast_join_bytes
        from ray_tpu.core.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG.query_broadcast_join_bytes

    def resolved_tenant(self) -> str:
        if self.tenant:
            return self.tenant
        import os

        return os.environ.get("RAY_TPU_JOB_ID") or "default"

    _instance: Optional["DataContext"] = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DataContext()
            return cls._instance
