"""DataContext: execution knobs (reference `python/ray/data/context.py:134`)."""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    # Streaming executor backpressure: max concurrent tasks per operator and
    # max buffered output blocks per operator before the op is throttled.
    max_tasks_in_flight_per_op: int = 8
    max_buffered_blocks_per_op: int = 16
    read_parallelism: int = -1  # -1 = auto (min(files, 2*CPUs, 192))
    eager_free: bool = True
    # Per-operator wall/rows stats (ds.stats()); one fire-and-forget
    # actor call per executed block when enabled.
    enable_stats: bool = True

    _instance: Optional["DataContext"] = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DataContext()
            return cls._instance
