"""Dataset: lazy, distributed data pipeline executed as tasks over the core.

Equivalent of the reference's `Dataset`/`Datastream`
(`python/ray/data/dataset.py`, `datastream.py:1096` streaming_split) with the
logical plan + streaming executor collapsed into one chain of fused block
transforms (`_internal/logical/`, `_internal/planner/planner.py`): every
consecutive 1:1 transform rides the same task, all-to-all ops (repartition,
random_shuffle) are materialization barriers, and consumption is streaming
(`iter_batches` starts before reads finish).

TPU-first choice: the canonical batch format is dict[str, np.ndarray] so
`iter_batches` output feeds `jax.device_put` without conversion.
"""

from __future__ import annotations

import itertools
import logging
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.context import DataContext

logger = logging.getLogger(__name__)

WorkItem = Tuple[Optional[Callable], tuple]


def _map_rows_transform(fn):
    def transform(block):
        return [fn(row) for row in BlockAccessor(block).rows()]

    transform._op_name = (
        f"Map({getattr(fn, '__name__', 'fn')})")
    return transform


def _flat_map_transform(fn):
    def transform(block):
        out = []
        for row in BlockAccessor(block).rows():
            out.extend(fn(row))
        return out

    transform._op_name = (
        f"FlatMap({getattr(fn, '__name__', 'fn')})")
    return transform


def _filter_transform(fn):
    def transform(block):
        acc = BlockAccessor(block)
        if isinstance(block, list):
            return [r for r in acc.rows() if fn(r)]
        batch = acc.to_batch()
        keep = np.asarray([bool(fn(row)) for row in acc.rows()])
        return {k: v[keep] for k, v in batch.items()}

    transform._op_name = (
        f"Filter({getattr(fn, '__name__', 'fn')})")
    return transform


def _map_batches_transform(fn, batch_size: Optional[int], fn_kwargs):
    def transform(block):
        acc = BlockAccessor(block)
        n = acc.num_rows()
        if n == 0:
            return block
        if batch_size is None or batch_size >= n:
            out = fn(acc.to_batch(), **fn_kwargs) if fn_kwargs else fn(acc.to_batch())
            return BlockAccessor.batch_to_block(out)
        pieces = []
        for start in range(0, n, batch_size):
            piece = BlockAccessor(acc.slice(start, min(start + batch_size, n)))
            out = fn(piece.to_batch(), **fn_kwargs) if fn_kwargs \
                else fn(piece.to_batch())
            pieces.append(BlockAccessor.batch_to_block(out))
        return BlockAccessor.concat(pieces)

    transform._op_name = (
        f"MapBatches({getattr(fn, '__name__', 'fn')})")
    return transform


def _stable_key_hash(k) -> int:
    """Deterministic cross-process hash that agrees wherever keys compare
    equal: np scalars unbox, bool/integral floats collapse to int (True,
    1, 1.0 and np.int64(1) all bucket together — a raw pickle hash would
    split one logical group across partitions). Strings hash by bytes
    (Python's str hash is per-process salted)."""
    import pickle as _pickle
    import zlib

    if hasattr(k, "item"):
        k = k.item()
    if isinstance(k, bool):
        k = int(k)
    elif isinstance(k, float) and k.is_integer():
        k = int(k)
    if isinstance(k, int):
        return k & 0x7FFFFFFF
    if isinstance(k, str):
        return zlib.crc32(k.encode())
    if isinstance(k, bytes):
        return zlib.crc32(k)
    if isinstance(k, tuple):
        h = 0x345678
        for x in k:
            h = (h * 1000003) ^ _stable_key_hash(x)
        return h & 0x7FFFFFFF
    return zlib.crc32(_pickle.dumps(k, protocol=4))


def _shuffle_map_block(block, n_out, mode, seed, salt, key_fn):
    """Map side of the push shuffle: scatter one block's rows into n_out
    bucket blocks (returned as separate objects via num_returns).

    Modes: "random" (seeded scatter), "hash" (stable key hash — groups
    co-locate), "keyed" (key_fn IS the partition assignment, row ->
    partition index — the query tier's range partitioner).

    Columnar fast path: a random or keyed scatter of a dict-of-arrays
    block slices arrays by the assignment vector instead of
    materializing one Python dict per row — the row->partition
    assignment is computed identically to the row path (same rng draw /
    same searchsorted-vs-bisect semantics), so bucket membership is
    representation-independent and deterministic either way."""
    from ray_tpu.data.block import _is_batch_dict

    columnar = _is_batch_dict(block) and block
    if mode == "random" and columnar:
        n = BlockAccessor(block).num_rows()
        rng = np.random.default_rng(
            None if seed is None else seed * 100003 + salt)
        assignment = rng.integers(0, n_out, size=n)
        if n_out == 1:
            return block
        return tuple({k: v[assignment == b] for k, v in block.items()}
                     for b in range(n_out))
    if mode == "keyed" and columnar and hasattr(key_fn, "assign_block"):
        assignment = key_fn.assign_block(block)
        if assignment is not None:
            if n_out == 1:
                return block
            return tuple({k: v[assignment == b] for k, v in block.items()}
                         for b in range(n_out))
    rows = list(BlockAccessor(block).rows())
    buckets: List[list] = [[] for _ in range(n_out)]
    if mode == "hash":
        for row in rows:
            k = key_fn(row) if key_fn else row
            buckets[_stable_key_hash(k) % n_out].append(row)
    elif mode == "keyed":
        for row in rows:
            buckets[int(key_fn(row)) % n_out].append(row)
    else:  # random scatter, deterministic per (seed, block salt)
        rng = np.random.default_rng(
            None if seed is None else seed * 100003 + salt)
        assignment = rng.integers(0, n_out, size=len(rows))
        for row, b in zip(rows, assignment):
            buckets[b].append(row)
    return buckets[0] if n_out == 1 else tuple(buckets)


def _shuffle_reduce_blocks(mode, seed, part_idx, *buckets):
    """Reduce side: concat this partition's buckets (+ local shuffle for
    random mode, so within-partition order is random too). Columnar
    buckets concat as arrays and shuffle via one permutation."""
    from ray_tpu.data.block import _is_batch_dict

    if buckets and all(_is_batch_dict(b) for b in buckets):
        merged = BlockAccessor.concat(list(buckets))
        if mode == "random":
            rng = np.random.default_rng(
                None if seed is None else seed * 7919 + part_idx)
            perm = rng.permutation(BlockAccessor(merged).num_rows())
            merged = {k: v[perm] for k, v in merged.items()}
        return merged
    rows: List[Any] = []
    for b in buckets:
        if _is_batch_dict(b):
            # Mixed representations (e.g. a union of columnar and row
            # parents): expand dict buckets to rows — extending the raw
            # dict would splice column NAMES into the data.
            rows.extend(BlockAccessor(b).rows())
        else:
            rows.extend(b)
    if mode == "random":
        rng = np.random.default_rng(
            None if seed is None else seed * 7919 + part_idx)
        rng.shuffle(rows)
    return rows


class ActorPoolStrategy:
    """Compute strategy for stateful map_batches UDFs (reference
    `ActorPoolStrategy` / `actor_pool_map_operator.py`): blocks flow
    through a pool of long-lived actors, each holding one instance of the
    UDF class — expensive setup (model load, jit compile) happens once per
    actor instead of once per block."""

    def __init__(self, size: Optional[int] = None, *, min_size: int = 1,
                 max_size: Optional[int] = None):
        self.size = size or max_size or max(min_size, 2)


class _MapWorker:
    """Actor body for ActorPoolStrategy stages."""

    def __init__(self, fn_cls, ctor_args, ctor_kwargs, batch_size, fn_kwargs):
        self._transform = _map_batches_transform(
            fn_cls(*ctor_args, **ctor_kwargs), batch_size, fn_kwargs)

    def apply(self, block):
        return self._transform(block)


class Dataset:
    """Lazy pipeline: `_work` produces input blocks, `_transforms` fuse."""

    def __init__(self, work: List[WorkItem],
                 transforms: Optional[List[Callable]] = None,
                 resources: Optional[dict] = None):
        self._work = work
        self._transforms = list(transforms or [])
        self._resources = resources
        self._materialized_refs: Optional[List[Any]] = None

    # ------------------------------------------------------------ transforms

    def _derive(self, transform: Callable) -> "Dataset":
        return Dataset(self._work, self._transforms + [transform],
                       self._resources)

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._derive(_map_rows_transform(fn))

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        return self._derive(_flat_map_transform(fn))

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._derive(_filter_transform(fn))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    fn_kwargs: Optional[Dict] = None,
                    compute: Optional["ActorPoolStrategy"] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[Dict] = None,
                    **_compat) -> "Dataset":
        if compute is not None or isinstance(fn, type):
            if not isinstance(fn, type):
                raise ValueError(
                    "compute=ActorPoolStrategy requires a callable CLASS "
                    "(stateful UDF), got a function")
            return _ActorStageDataset(
                self, fn, batch_size, fn_kwargs or {},
                tuple(fn_constructor_args), fn_constructor_kwargs or {},
                compute or ActorPoolStrategy())
        return self._derive(_map_batches_transform(fn, batch_size,
                                                   fn_kwargs or {}))

    def limit(self, n: int) -> "Dataset":
        """First `n` rows. Executes streaming with early stop (the
        reference's limit pushdown: upstream tasks past the cutoff are
        never launched because the pull stops)."""
        parent = self

        def work() -> List[WorkItem]:
            out: List[WorkItem] = []
            remaining = n
            for block in parent._iter_block_values():
                acc = BlockAccessor(block)
                take = min(acc.num_rows(), remaining)
                if take > 0:
                    out.append((None, (acc.slice(0, take),)))
                    remaining -= take
                if remaining <= 0:
                    break
            return out

        return _DeferredDataset(work)

    def sort(self, key: Optional[Any] = None, descending: bool = False
             ) -> "Dataset":
        """Distributed global sort: sample-based range partitioning
        through the windowed shuffle, per-partition stable local sort
        (ray_tpu/data/query/sort.py). The driver holds only the boundary
        sample (bounded by `query_sort_sample_rows`), never rows —
        output is row-identical to a driver-side stable sort for any
        sample draw."""
        from ray_tpu.data.query.sort import sort_dataset

        return sort_dataset(self, key, descending)

    def join(self, other: "Dataset", on,
             how: str = "inner") -> "Dataset":
        """Distributed join (ray_tpu/data/query/join.py): broadcast when
        `other` (the build side) fits `query_broadcast_join_bytes`,
        hash-shuffle exchange of both sides otherwise. `on` is a column
        name or a (left_col, right_col) pair; `how` is "inner" or
        "left". Colliding non-key columns from `other` get the zip()
        "_1" suffix."""
        from ray_tpu.data.query.join import join_datasets

        return join_datasets(self, other, on, how)

    def with_resources(self, **resources) -> "Dataset":
        """Run this dataset's tasks with resource options (e.g. num_cpus).
        Type-preserving: subclasses carry their plan state along."""
        out = self._copy()
        out._resources = resources
        return out

    def _copy(self) -> "Dataset":
        return Dataset(self._work, self._transforms, self._resources)

    # ----------------------------------------------------------- all-to-all

    def repartition(self, num_blocks: int, *,
                    shuffle: bool = False) -> "Dataset":
        """Rebalance into num_blocks blocks. shuffle=True runs the
        distributed push shuffle instead of the driver-side re-slice
        (reference repartition(shuffle=True))."""
        if shuffle:
            return self._push_shuffle(mode="random", seed=0,
                                      num_blocks=num_blocks)
        parent = self

        def work() -> List[WorkItem]:
            # raylint: disable=RL019 — documented driver-side re-slice; width-scale callers pass shuffle=True
            blocks = [b for b in parent._iter_block_values()]
            merged = BlockAccessor.concat(blocks) if blocks else []
            total = BlockAccessor(merged).num_rows()
            per = max(1, -(-total // num_blocks))
            acc = BlockAccessor(merged)
            out: List[WorkItem] = []
            for i in range(num_blocks):
                start = min(i * per, total)
                end = min((i + 1) * per, total)
                out.append((None, (acc.slice(start, end),)))
            return out

        return _DeferredDataset(work)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global shuffle as a push-based two-stage exchange (reference
        `push_based_shuffle.py`): map tasks scatter each block's rows into
        per-output buckets (one return object per bucket, so a reducer
        pulls only its slice), reduce tasks concat + locally shuffle. The
        driver never materializes the data."""
        return self._push_shuffle(mode="random", seed=seed)

    def _push_shuffle(self, *, mode: str, seed: Optional[int] = None,
                      key_fn: Optional[Callable[[Any], Any]] = None,
                      num_blocks: Optional[int] = None) -> "Dataset":
        return _WindowedShuffleDataset(self, mode, seed, key_fn, num_blocks)

    def union(self, *others: "Dataset") -> "Dataset":
        sets = [self, *others]

        def work() -> List[WorkItem]:
            out: List[WorkItem] = []
            for ds in sets:
                for ref in ds._iter_block_refs():
                    out.append((None, (ref,)))
            return out

        return _DeferredDataset(work)

    # ------------------------------------------------------------- execution

    def _iter_block_refs(self) -> Iterator[Any]:
        """Streaming execution: yields ObjectRefs to output blocks."""
        if self._materialized_refs is not None:
            yield from self._materialized_refs
            return
        yield from self._execute_work(iter(self._work))

    def _ensure_collector(self):
        from ray_tpu.data.context import DataContext

        if not DataContext.get_current().enable_stats:
            return None
        from ray_tpu.data import stats as stats_mod

        # One collector per Dataset, reused across executions and
        # reaped with the Dataset object (a per-execution actor
        # would leak one worker process per epoch).
        collector = getattr(self, "_stats_collector", None)
        if collector is None:
            collector = stats_mod.make_collector()
            self._stats_collector = collector
        return collector

    def _execute_work(self, work_iter, lineage=None) -> Iterator[Any]:
        """Run one streaming execution over `work_iter` (shared by the
        plan path and the windowed-shuffle path): byte-budgeted executor,
        per-dataset stats collector, per-execution block lineage (shared
        with an upstream shuffle stage when passed in)."""
        from ray_tpu.data.executor import StreamingExecutor
        from ray_tpu.data.streaming.lineage import BlockLineage

        collector = self._ensure_collector()
        if lineage is None:
            lineage = BlockLineage()
        self._lineage = lineage
        executor = StreamingExecutor(self._transforms,
                                     resources=self._resources,
                                     stats_collector=collector,
                                     lineage=lineage)
        # Cumulative across executions: the collector aggregates every
        # run of this Dataset, so the stats() flush barrier must expect
        # the total, not just the latest run's blocks.
        if getattr(self, "_executed_blocks", None) is None:
            self._executed_blocks = 0
        try:
            for ref in executor.execute(work_iter):
                self._executed_blocks += 1
                yield ref
        finally:
            self._last_budget_stats = executor.last_budget_stats
            lineage.clear()  # recipes drain with the execution

    def stats(self):
        """Per-operator wall/rows/blocks summary, aggregated over every
        execution of this Dataset so far (re-iterating a lazy dataset
        adds to the totals — reference `Dataset.stats()`,
        `data/_internal/stats.py`), plus the LAST execution's per-op
        byte-budget backpressure (`.backpressure` — where the pipeline
        is bound). None before any execution."""
        from ray_tpu.data import stats as stats_mod

        return stats_mod.fetch(getattr(self, "_stats_collector", None),
                               expected_blocks=getattr(
                                   self, "_executed_blocks", None),
                               backpressure=getattr(
                                   self, "_last_budget_stats", None))

    def _iter_block_values(self) -> Iterator[Block]:
        import ray_tpu

        for ref in self._iter_block_refs():
            # Data-tier lineage fallback: a block the core could not
            # recover re-runs from its recorded recipe, bounded.
            lineage = getattr(self, "_lineage", None)
            if lineage is not None:
                yield lineage.resolve(ref)
            else:
                yield ray_tpu.get(ref)

    def materialize(self) -> "Dataset":
        refs = list(self._iter_block_refs())
        out = Dataset(self._work, self._transforms, self._resources)
        out._stats_collector = getattr(self, "_stats_collector", None)
        out._executed_blocks = getattr(self, "_executed_blocks", None)
        out._materialized_refs = refs
        # Keep a plan for re-execution-from-refs.
        out._work = [(None, (r,)) for r in refs]
        out._transforms = []
        return out

    # ------------------------------------------------------------ consumers

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_block_values():
            yield from BlockAccessor(block).rows()

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False,
                     prefetch_batches: int = 1) -> Iterator[Dict[str, np.ndarray]]:
        from ray_tpu.data.iterator import batch_blocks

        yield from batch_blocks(self._iter_block_values(), batch_size,
                                drop_last)

    def iterator(self):
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self)

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for block in self._iter_block_values():
            out.extend(BlockAccessor(block).take(limit - len(out)))
            if len(out) >= limit:
                break
        return out[:limit]

    def take_all(self) -> List[Any]:
        # raylint: disable=RL019 — the deliberate driver-resident endpoint: the caller asked for a local copy
        return [r for r in self.iter_rows()]

    def count(self) -> int:
        return sum(BlockAccessor(b).num_rows()
                   for b in self._iter_block_values())

    def schema(self):
        for block in self._iter_block_values():
            acc = BlockAccessor(block)
            if acc.num_rows():
                return acc.schema()
        return None

    def num_blocks(self) -> int:
        return len(self._work)

    # ------------------------------------------------------------ column ops

    def add_column(self, name: str, fn: Callable[[Any], Any]) -> "Dataset":
        """Add a column computed per row (reference Dataset.add_column)."""
        return self.map(lambda r: {**r, name: fn(r)})

    def drop_columns(self, cols: List[str]) -> "Dataset":
        drop = set(cols)
        return self.map(
            lambda r: {k: v for k, v in r.items() if k not in drop})

    def select_columns(self, cols: List[str]) -> "Dataset":
        keep = list(cols)
        return self.map(lambda r: {k: r[k] for k in keep})

    def unique(self, column: str) -> List[Any]:
        """Distinct values of a column: per-block sets union'd on the
        driver (map-side dedup keeps the transfer small)."""
        def transform(block):
            seen = {row[column] for row in BlockAccessor(block).rows()}
            return [{"u": v} for v in seen]

        out = set()
        for b in self._derive(transform)._iter_block_values():
            for row in BlockAccessor(b).rows():
                out.add(row["u"])
        try:
            return sorted(out)
        except TypeError:  # mixed/unorderable values
            return list(out)

    def zip(self, other: "Dataset") -> "Dataset":
        """Positional zip (reference Dataset.zip): rows pair up in order;
        dict rows merge (collisions suffixed _1), others become tuples.
        All-to-all barrier — deferred until consumed, like repartition."""
        parent, rhs = self, other

        def work() -> List[WorkItem]:
            right_rows = []
            for b in rhs._iter_block_values():
                right_rows.extend(BlockAccessor(b).rows())
            blocks: List[Block] = []
            pos = 0
            for b in parent._iter_block_values():
                merged = []
                for r in BlockAccessor(b).rows():
                    if pos >= len(right_rows):
                        raise ValueError(
                            "zip: datasets have different lengths")
                    o = right_rows[pos]
                    pos += 1
                    if isinstance(r, dict) and isinstance(o, dict):
                        m = dict(r)
                        for k, v in o.items():
                            m[f"{k}_1" if k in m else k] = v
                        merged.append(m)
                    else:
                        merged.append((r, o))
                blocks.append(merged)
            if pos != len(right_rows):
                raise ValueError("zip: datasets have different lengths")
            return [(None, (b,)) for b in blocks]

        return _DeferredDataset(work)

    # --------------------------------------------------------------- groupby

    def groupby(self, key: Union[str, Callable[[Any], Any]]) -> "GroupedData":
        """Group rows by a column name or key function (reference
        Dataset.groupby -> GroupedData)."""
        return GroupedData(self, key)

    def sum(self, on: Optional[str] = None):
        return self._agg(np.sum, on)

    def mean(self, on: Optional[str] = None):
        total, rows = 0.0, 0
        for b in self._iter_block_values():
            acc = BlockAccessor(b)
            batch = acc.to_batch()
            col = batch[on] if on else next(iter(batch.values()))
            total += float(np.sum(col))
            rows += len(col)
        return total / rows if rows else 0.0

    def min(self, on: Optional[str] = None):
        return self._agg(np.min, on, reducer=min)

    def max(self, on: Optional[str] = None):
        return self._agg(np.max, on, reducer=max)

    def _agg(self, fn, on, reducer=None):
        parts = []
        for b in self._iter_block_values():
            batch = BlockAccessor(b).to_batch()
            col = batch[on] if on else next(iter(batch.values()))
            if len(col):
                parts.append(fn(col))
        if not parts:
            return None
        if reducer:
            out = parts[0]
            for p in parts[1:]:
                out = reducer(out, p)
            return out
        return float(np.sum(parts)) if fn is np.sum else fn(parts)

    # ---------------------------------------------------------------- splits

    def split(self, n: int) -> List["Dataset"]:
        """Materializing split into n datasets with balanced rows."""
        refs = list(self.materialize()._iter_block_refs())
        groups: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            groups[i % n].append(ref)
        out = []
        for g in groups:
            ds = Dataset([(None, (r,)) for r in g])
            out.append(ds)
        return out

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List[Any]:
        """n coordinated iterators over ONE shared streaming execution
        (reference `datastream.py:1096` -> `StreamSplitDataIterator`)."""
        from ray_tpu.data.iterator import make_streaming_splits

        return make_streaming_splits(self, n, equal=equal)

    # ---------------------------------------------------------------- writes

    def write_parquet(self, path: str) -> List[str]:
        return self._write(path, "parquet")

    def write_csv(self, path: str) -> List[str]:
        return self._write(path, "csv")

    def write_json(self, path: str) -> List[str]:
        return self._write(path, "json")

    def write_numpy(self, path: str, column: str = "item") -> List[str]:
        return self._write(path, "numpy", column=column)

    def write_webdataset(self, path: str) -> List[str]:
        """One tar shard per block; rows must be dicts whose keys are
        webdataset extensions (plus optional __key__)."""
        return self._write(path, "webdataset")

    def _write(self, path: str, fmt: str, **kw) -> List[str]:
        import os

        import ray_tpu
        from ray_tpu.data.datasource import write_block

        os.makedirs(path, exist_ok=True)
        refs = []
        for i, block_ref in enumerate(self._iter_block_refs()):
            refs.append(ray_tpu.remote(write_block).remote(
                block_ref, path, i, fmt, kw))
        return ray_tpu.get(refs)

    def to_pandas(self):
        import pandas as pd

        # raylint: disable=RL019 — a DataFrame IS a local copy; the caller opted out of the streaming plane
        blocks = [BlockAccessor(b).to_pandas()
                  for b in self._iter_block_values()]
        return pd.concat(blocks, ignore_index=True) if blocks else pd.DataFrame()

    def __repr__(self):
        return (f"Dataset(num_blocks={len(self._work)}, "
                f"num_transforms={len(self._transforms)})")


class _ActorStageDataset(Dataset):
    """map_batches over an actor pool: the parent's output refs stream
    through `size` long-lived _MapWorker actors with bounded in-flight
    (reference `actor_pool_map_operator.py`); downstream 1:1 transforms
    fuse into tasks over the stage's outputs as usual."""

    def __init__(self, parent: Dataset, fn_cls, batch_size, fn_kwargs,
                 ctor_args, ctor_kwargs, strategy: ActorPoolStrategy,
                 transforms: Optional[List[Callable]] = None,
                 resources: Optional[dict] = None):
        super().__init__([], transforms, resources or parent._resources)
        self._parent = parent
        self._stage = (fn_cls, batch_size, fn_kwargs, ctor_args, ctor_kwargs,
                       strategy)

    def _derive(self, transform: Callable) -> "Dataset":
        return _ActorStageDataset(self._parent, *self._stage[:5],
                                  self._stage[5],
                                  self._transforms + [transform],
                                  self._resources)

    def _copy(self) -> "Dataset":
        return _ActorStageDataset(self._parent, *self._stage[:5],
                                  self._stage[5], list(self._transforms),
                                  self._resources)

    def _actor_output_refs(self) -> Iterator[Any]:
        import ray_tpu

        fn_cls, batch_size, fn_kwargs, ctor_args, ctor_kwargs, strat = \
            self._stage
        actor_cls = ray_tpu.remote(_MapWorker)
        if self._resources:
            actor_cls = actor_cls.options(**self._resources)
        actors = [actor_cls.remote(fn_cls, ctor_args, ctor_kwargs,
                                   batch_size, fn_kwargs)
                  for _ in range(strat.size)]
        try:
            upstream = self._parent._iter_block_refs()
            in_flight: Dict[Any, Any] = {}  # result ref -> actor
            free = list(actors)
            exhausted = False
            while True:
                while free and not exhausted:
                    try:
                        block_ref = next(upstream)
                    except StopIteration:
                        exhausted = True
                        break
                    actor = free.pop()
                    in_flight[actor.apply.remote(block_ref)] = actor
                if not in_flight:
                    if exhausted:
                        return
                    continue
                ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                        timeout=30.0)
                for ref in ready:
                    free.append(in_flight.pop(ref))
                    yield ref
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001
                    pass

    def _iter_block_refs(self) -> Iterator[Any]:
        if self._materialized_refs is not None:
            yield from self._materialized_refs
            return
        refs = self._actor_output_refs()
        if not self._transforms:
            yield from refs
            return
        from ray_tpu.data.executor import StreamingExecutor

        executor = StreamingExecutor(self._transforms,
                                     resources=self._resources)
        yield from executor.execute((None, (ref,)) for ref in refs)

    def num_blocks(self) -> int:
        return self._parent.num_blocks()


class _DeferredDataset(Dataset):
    """Dataset whose inputs come from a barrier (all-to-all) computation;
    the work list is computed on first execution and cached."""

    def __init__(self, work_fn: Callable[[], List[WorkItem]],
                 transforms: Optional[List[Callable]] = None,
                 resources: Optional[dict] = None):
        super().__init__([], transforms, resources)
        self._work_fn = work_fn
        self._resolved = False

    def _derive(self, transform: Callable) -> "Dataset":
        return _DeferredDataset(self._work_fn,
                                self._transforms + [transform],
                                self._resources)

    def _copy(self) -> "Dataset":
        return _DeferredDataset(self._work_fn, list(self._transforms),
                                self._resources)

    def _resolve(self):
        if not self._resolved:
            self._work = self._work_fn()
            self._resolved = True

    def _iter_block_refs(self) -> Iterator[Any]:
        self._resolve()
        yield from super()._iter_block_refs()

    def num_blocks(self) -> int:
        self._resolve()
        return len(self._work)


class _WindowedShuffleDataset(Dataset):
    """All-to-all exchange executed as the WINDOWED streaming plan
    (ray_tpu/data/streaming/shuffle.py): parent blocks stream through
    budget-bounded scatter windows whose sealed buckets spill through the
    store's disk tier when the working set exceeds memory, then reduce
    with bounded admission. Row-level output is identical to the seed-era
    exchange for a given (mode, seed).

    Re-iterating RE-WINDOWS: every epoch re-runs the exchange (and the
    parent pipeline feeding it) instead of re-materializing the shuffled
    dataset — multi-epoch train ingest holds one window of intermediates,
    not the whole dataset. `materialize()` still pins an epoch's outputs
    when a caller wants them resident."""

    def __init__(self, parent: Dataset, mode: str, seed: Optional[int],
                 key_fn: Optional[Callable[[Any], Any]],
                 num_blocks: Optional[int],
                 transforms: Optional[List[Callable]] = None,
                 resources: Optional[dict] = None):
        super().__init__([], transforms, resources or parent._resources)
        self._parent = parent
        self._shuffle_plan = (mode, seed, key_fn, num_blocks)
        # Filled per execution: windows / input_bytes / window_bytes.
        self.last_shuffle_stats: Dict[str, Any] = {}

    def _derive(self, transform: Callable) -> "Dataset":
        return _WindowedShuffleDataset(self._parent, *self._shuffle_plan,
                                       self._transforms + [transform],
                                       self._resources)

    def _copy(self) -> "Dataset":
        return _WindowedShuffleDataset(self._parent, *self._shuffle_plan,
                                       list(self._transforms),
                                       self._resources)

    def num_blocks(self) -> int:
        n_out = self._shuffle_plan[3]
        return n_out if n_out else self._parent.num_blocks()

    def _iter_block_refs(self) -> Iterator[Any]:
        if self._materialized_refs is not None:
            yield from self._materialized_refs
            return
        from ray_tpu.data.streaming.budget import pipeline_budget
        from ray_tpu.data.streaming.shuffle import iter_shuffled_refs

        mode, seed, key_fn, _ = self._shuffle_plan
        n_out = self.num_blocks()
        if n_out <= 0:
            return
        from ray_tpu.data.streaming.lineage import BlockLineage

        collector = self._ensure_collector()
        lineage = BlockLineage()
        stats: Dict[str, Any] = {}
        with pipeline_budget() as budget:
            reduce_refs = iter_shuffled_refs(
                self._parent._iter_block_refs(), n_out, mode=mode,
                seed=seed, key_fn=key_fn, budget=budget,
                stage_stats=collector, stats=stats,
                resources=self._resources, lineage=lineage)
            try:
                if not self._transforms:
                    # No downstream transforms: reduce outputs ARE the
                    # dataset's blocks — yield them directly instead of
                    # paying an identity fused task per block (and keep
                    # the lineage chain one level deep for recovery).
                    self._lineage = lineage
                    yield from reduce_refs
                else:
                    yield from self._execute_work(
                        ((None, (r,)) for r in reduce_refs),
                        lineage=lineage)
            finally:
                self.last_shuffle_stats = stats
                if not self._transforms:
                    self._last_budget_stats = budget.stats()
                    lineage.clear()


class _RangeSortDataset(Dataset):
    """Distributed sort (ray_tpu/data/query/sort.py): bounded remote key
    sample -> range boundaries -> keyed windowed exchange -> fused stable
    local sort. Inherits the windowed shuffle's budget/spill/lineage
    behavior; `last_sort_stats` records the driver-resident sample bytes
    (the operator's entire driver footprint) for assertion."""

    def __init__(self, parent: Dataset, key, descending: bool,
                 lenient: bool = False,
                 transforms: Optional[List[Callable]] = None,
                 resources: Optional[dict] = None):
        super().__init__([], transforms, resources or parent._resources)
        self._parent = parent
        self._sort_plan = (key, descending, lenient)
        self.last_sort_stats: Dict[str, Any] = {}
        self.last_shuffle_stats: Dict[str, Any] = {}

    def _derive(self, transform: Callable) -> "Dataset":
        return _RangeSortDataset(self._parent, *self._sort_plan,
                                 self._transforms + [transform],
                                 self._resources)

    def _copy(self) -> "Dataset":
        return _RangeSortDataset(self._parent, *self._sort_plan,
                                 list(self._transforms), self._resources)

    def num_blocks(self) -> int:
        return max(1, self._parent.num_blocks())

    def _sample_boundaries(self, parent_refs, key, n_parts):
        """Remote per-block key samples -> sorted boundary cut points.
        Driver-resident state is KEYS ONLY, bounded by
        `query_sort_sample_rows`; `last_sort_stats` carries the measured
        byte count so tests can assert the bound. Raises TypeError for
        unorderable key mixtures (callers in lenient mode catch it)."""
        import ray_tpu
        from ray_tpu.core import serialization
        from ray_tpu.data.context import DataContext
        from ray_tpu.data.query.sort import (_sample_block_keys,
                                             compute_boundaries)

        ctx = DataContext.get_current()
        sample_rows = max(n_parts, ctx.resolved_sort_sample_rows())
        per_block = max(1, -(-sample_rows // len(parent_refs)))
        sampler = ray_tpu.remote(_sample_block_keys)
        if self._resources:
            sampler = sampler.options(**self._resources)
        sample_refs = [
            sampler.remote(ref, per_block, key, 0, salt)
            for salt, ref in enumerate(parent_refs)]
        # bounded-sample: per_block * n_blocks ~= query_sort_sample_rows
        # keys total — never rows, never unbounded.
        samples = [k for part in ray_tpu.get(sample_refs) for k in part]
        if len(samples) > sample_rows:  # cap exactly, not just ~per-block
            rng = np.random.default_rng(0)
            keep = sorted(rng.choice(len(samples), size=sample_rows,
                                     replace=False).tolist())
            samples = [samples[i] for i in keep]
        boundaries = compute_boundaries(samples, n_parts)
        self.last_sort_stats = {
            "sample_rows": len(samples),
            "driver_sample_bytes": serialization.serialized_size(
                serialization.serialize(samples)),
            "n_parts": n_parts,
        }
        return boundaries

    def _iter_block_refs(self) -> Iterator[Any]:
        if self._materialized_refs is not None:
            yield from self._materialized_refs
            return
        from ray_tpu.data.executor import StreamingExecutor
        from ray_tpu.data.query.sort import (_RangePartitioner,
                                             make_local_sort_transform)
        from ray_tpu.data.streaming.budget import pipeline_budget
        from ray_tpu.data.streaming.lineage import BlockLineage
        from ray_tpu.data.streaming.shuffle import iter_shuffled_refs

        key, descending, lenient = self._sort_plan
        # Parent executes ONCE; refs (not data) are held so the sample
        # and scatter passes read the same blocks. Sealed parents spill
        # under pressure, so pinning refs is disk-bounded, not RAM.
        parent_refs = list(self._parent._iter_block_refs())
        if not parent_refs:
            return
        n_parts = self.num_blocks()
        try:
            boundaries = self._sample_boundaries(parent_refs, key, n_parts)
        except TypeError:
            if not lenient:
                raise
            # Unorderable key mixture: degrade to unsorted passthrough
            # (the groupby result-ordering contract).
            yield from self._execute_work(
                ((None, (r,)) for r in parent_refs))
            return
        partitioner = _RangePartitioner(boundaries, key, descending,
                                        n_parts)
        collector = self._ensure_collector()
        lineage = BlockLineage()
        stats: Dict[str, Any] = {}
        with pipeline_budget() as budget:
            reduce_refs = iter_shuffled_refs(
                iter(parent_refs), n_parts, mode="keyed", seed=0,
                key_fn=partitioner, budget=budget, stage_stats=collector,
                stats=stats, resources=self._resources, lineage=lineage)
            transforms = [make_local_sort_transform(key, descending,
                                                    lenient)]
            transforms += self._transforms
            executor = StreamingExecutor(transforms,
                                         resources=self._resources,
                                         stats_collector=collector,
                                         lineage=lineage)
            self._lineage = lineage
            if getattr(self, "_executed_blocks", None) is None:
                self._executed_blocks = 0
            try:
                for ref in executor.execute(
                        (None, (r,)) for r in reduce_refs):
                    self._executed_blocks += 1
                    yield ref
            finally:
                self.last_shuffle_stats = stats
                self._last_budget_stats = executor.last_budget_stats
                lineage.clear()


class _JoinDataset(Dataset):
    """Distributed join (ray_tpu/data/query/join.py). Strategy picked at
    iteration time from the build side's actual sealed bytes: broadcast
    (right refs ride every probe task's args; the store ships each right
    block to a node at most once) or hash exchange of BOTH sides through
    the windowed shuffle under ONE shared pipeline budget.
    `last_join_stats` records the decision + build size."""

    def __init__(self, parent: Dataset, right: Dataset, left_on: str,
                 right_on: str, how: str,
                 transforms: Optional[List[Callable]] = None,
                 resources: Optional[dict] = None):
        super().__init__([], transforms, resources or parent._resources)
        self._parent = parent
        self._join_plan = (right, left_on, right_on, how)
        self.last_join_stats: Dict[str, Any] = {}

    def _derive(self, transform: Callable) -> "Dataset":
        return _JoinDataset(self._parent, *self._join_plan,
                            self._transforms + [transform],
                            self._resources)

    def _copy(self) -> "Dataset":
        return _JoinDataset(self._parent, *self._join_plan,
                            list(self._transforms), self._resources)

    def num_blocks(self) -> int:
        return max(1, self._parent.num_blocks())

    def _iter_block_refs(self) -> Iterator[Any]:
        if self._materialized_refs is not None:
            yield from self._materialized_refs
            return
        from ray_tpu.data.context import DataContext
        from ray_tpu.data.executor import StreamingExecutor
        from ray_tpu.data.query.join import (_KeyGetter,
                                             join_partition_blocks)
        from ray_tpu.data.streaming.budget import pipeline_budget
        from ray_tpu.data.streaming.lineage import BlockLineage
        from ray_tpu.data.streaming.shuffle import (_block_size,
                                                    iter_shuffled_refs)

        right, left_on, right_on, how = self._join_plan
        ctx = DataContext.get_current()
        # Build side materializes to refs either way: broadcast ships
        # them to every probe, hash exchange re-scatters them. Sizes
        # come from the object directory, not from pulling data.
        right_refs = list(right._iter_block_refs())
        est_default = ctx.target_min_block_size
        build_bytes = sum(_block_size(r) or est_default
                          for r in right_refs)
        threshold = ctx.resolved_broadcast_join_bytes()
        broadcast = build_bytes <= threshold
        self.last_join_stats = {
            "strategy": "broadcast" if broadcast else "hash",
            "build_bytes": build_bytes,
            "broadcast_threshold": threshold,
        }
        collector = self._ensure_collector()
        lineage = BlockLineage()
        self._lineage = lineage
        executor = StreamingExecutor(self._transforms,
                                     resources=self._resources,
                                     stats_collector=collector,
                                     lineage=lineage)
        if getattr(self, "_executed_blocks", None) is None:
            self._executed_blocks = 0

        def _run(work_iter):
            try:
                for ref in executor.execute(work_iter):
                    self._executed_blocks += 1
                    yield ref
            finally:
                self._last_budget_stats = executor.last_budget_stats
                lineage.clear()

        if broadcast:
            yield from _run(
                (join_partition_blocks,
                 (left_on, right_on, how, None, lref, *right_refs))
                for lref in self._parent._iter_block_refs())
            return
        rcols_hint = None
        if how == "left":
            # Left-join None-fill needs the GLOBAL right column set — a
            # hash partition may receive none (or a columnar subset) of
            # the build rows yet must still emit the same schema as the
            # broadcast strategy. Column NAMES are bounded metadata, so
            # this stays within the driver's sample-sized footprint.
            from ray_tpu.data.query.join import right_block_columns
            import ray_tpu
            col_task = ray_tpu.remote(right_block_columns)
            # raylint: disable=RL019 — bounded metadata: column names only, one short list per build block
            col_lists = ray_tpu.get([col_task.remote(r)
                                     for r in right_refs])
            seen_cols: set = set()
            rcols_hint = []
            for cols in col_lists:
                for c in cols:
                    if c not in seen_cols:
                        seen_cols.add(c)
                        rcols_hint.append(c)
        n_parts = self.num_blocks()
        lstats: Dict[str, Any] = {}
        rstats: Dict[str, Any] = {}
        with pipeline_budget() as budget:
            lgen = iter_shuffled_refs(
                self._parent._iter_block_refs(), n_parts, mode="hash",
                seed=0, key_fn=_KeyGetter(left_on), budget=budget,
                stage_stats=collector, stats=lstats,
                resources=self._resources, lineage=lineage)
            rgen = iter_shuffled_refs(
                iter(right_refs), n_parts, mode="hash", seed=0,
                key_fn=_KeyGetter(right_on), budget=budget,
                stage_stats=collector, stats=rstats,
                resources=self._resources, lineage=lineage)
            try:
                yield from _run(
                    (join_partition_blocks,
                     (left_on, right_on, how, rcols_hint, lref, rref))
                    for lref, rref in zip(lgen, rgen))
            finally:
                lgen.close()
                rgen.close()
                self.last_join_stats["left_shuffle"] = lstats
                self.last_join_stats["right_shuffle"] = rstats


class GroupedData:
    """Result of `Dataset.groupby`: the distributed hash-aggregate plan
    (ray_tpu/data/query/aggregate.py) — per-block partial aggregation,
    hash scatter of the partials through the windowed shuffle, merge +
    finalize on the reducers, range-sorted output. Rows never transit
    the driver. Aggregations return a Dataset of `{key, <agg>}` rows
    sorted by key (when orderable); `map_groups` applies a function to
    each group's rows in parallel tasks.
    """

    def __init__(self, ds: Dataset, key: Union[str, Callable[[Any], Any]]):
        self._ds = ds
        self._key = key

    def _key_fn(self) -> Callable[[Any], Any]:
        k = self._key
        if callable(k):
            return k
        return lambda row: row[k]

    def _key_name(self) -> str:
        return self._key if isinstance(self._key, str) else "key"

    def aggregate(self, *aggs) -> Dataset:
        """Run composable AggregateFns (ray_tpu/data/query/aggregate.py)
        through the distributed hash-aggregate plan; one result row per
        key, columns named by each aggregation."""
        from ray_tpu.data.query.aggregate import grouped_aggregate

        return grouped_aggregate(self._ds, self._key, self._key_name(),
                                 list(aggs))

    def count(self) -> Dataset:
        from ray_tpu.data.query.aggregate import Count

        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        from ray_tpu.data.query.aggregate import Sum

        return self.aggregate(Sum(on))

    def mean(self, on: str) -> Dataset:
        from ray_tpu.data.query.aggregate import Mean

        return self.aggregate(Mean(on))

    def min(self, on: str) -> Dataset:
        from ray_tpu.data.query.aggregate import Min

        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        from ray_tpu.data.query.aggregate import Max

        return self.aggregate(Max(on))

    def map_groups(self, fn: Callable[[List[Any]], Any]) -> Dataset:
        """Apply `fn` to each group's full row list; fn returns a row or a
        list of rows. Rows route to partitions by key hash through the
        push shuffle (all of a group's rows land in one partition without
        transiting the driver); each partition task then groups locally
        and applies fn per group."""
        keyf = self._key_fn()
        shuffled = self._ds._push_shuffle(mode="hash", key_fn=keyf)

        def transform(block):
            groups: Dict[Any, List[Any]] = {}
            for row in BlockAccessor(block).rows():
                groups.setdefault(keyf(row), []).append(row)
            out: List[Any] = []
            for rows in groups.values():
                res = fn(rows)
                out.extend(res if isinstance(res, list) else [res])
            return out

        return shuffled._derive(transform)
