"""Datasources: file readers/writers run inside read tasks.

Equivalent of the reference's `python/ray/data/datasource/*_datasource.py`
(parquet, csv, json, text, numpy, binary) + `file_based_datasource.py` path
expansion. Each reader returns one block per file chunk; the read happens in
the task, so bytes never flow through the driver.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List, Optional

import numpy as np


def expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in glob.glob(os.path.join(p, "**", "*"), recursive=True)
                if os.path.isfile(f) and not os.path.basename(f).startswith((".", "_"))))
        elif any(c in p for c in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"No input files found for {paths}")
    return out


# -------------------------------------------------------------- partitioning #


class Partitioning:
    """Path-based partitioning scheme (reference
    `python/ray/data/datasource/partitioning.py` Partitioning /
    PathPartitionParser).

    style="hive": `.../year=2024/country=de/part.parquet` -> columns
    {year: "2024", country: "de"}.
    style="dir": positional `field_names` map path directories under
    `base_dir` to columns: field_names=["year", "country"] parses
    `.../2024/de/part.parquet`.
    """

    def __init__(self, style: str = "hive",
                 base_dir: Optional[str] = None,
                 field_names: Optional[List[str]] = None):
        if style not in ("hive", "dir"):
            raise ValueError(f"unknown partitioning style {style!r}")
        if style == "dir" and not field_names:
            raise ValueError("style='dir' requires field_names")
        self.style = style
        self.base_dir = os.path.normpath(base_dir) if base_dir else None
        self.field_names = list(field_names or [])

    def parse(self, path: str) -> Dict[str, str]:
        """Partition column values encoded in `path` (empty when none)."""
        rel = os.path.dirname(os.path.abspath(path))
        if self.base_dir:
            base = os.path.abspath(self.base_dir)
            # Containment, not string prefix: /data/tbl_backup must not
            # read as inside /data/tbl.
            if rel != base and not rel.startswith(base + os.sep):
                return {}
            rel = rel[len(base):].lstrip(os.sep)
        parts = [p for p in rel.split(os.sep) if p]
        if self.style == "hive":
            out = {}
            for p in parts:
                if "=" in p:
                    k, _, v = p.partition("=")
                    out[k] = v
            return out
        # dir style: the LAST len(field_names) directories map by position.
        tail = parts[-len(self.field_names):]
        if len(tail) < len(self.field_names):
            return {}
        return dict(zip(self.field_names, tail))


def attach_partition_columns(block: Any, parts: Dict[str, str]) -> Any:
    """Append constant partition columns to a block (tabular blocks:
    pandas / arrow / dict-of-arrays / list-of-dict rows)."""
    if not parts:
        return block
    try:
        import pandas as pd

        if isinstance(block, pd.DataFrame):
            for k, v in parts.items():
                if k not in block.columns:
                    block[k] = v
            return block
    except ImportError:
        pass
    try:
        import pyarrow as pa

        if isinstance(block, pa.Table):
            n = block.num_rows
            for k, v in parts.items():
                if k not in block.column_names:
                    block = block.append_column(k, pa.array([v] * n))
            return block
    except ImportError:
        pass
    if isinstance(block, dict):
        n = len(next(iter(block.values()))) if block else 0
        for k, v in parts.items():
            block.setdefault(k, np.full(n, v, dtype=object))
        return block
    if isinstance(block, list) and block and isinstance(block[0], dict):
        for row in block:
            for k, v in parts.items():
                row.setdefault(k, v)
        return block
    return block


def partitioned_reader(reader, path: str,
                       partitioning: Optional[Partitioning], *args, **kw):
    """Wrap a per-file reader: parse the path's partition values and
    attach them as columns."""
    block = reader(path, *args, **kw)
    if partitioning is not None:
        block = attach_partition_columns(block, partitioning.parse(path))
    return block


# ------------------------------------------------------------------ readers #


def read_parquet_file(path: str, columns: Optional[List[str]] = None):
    import pyarrow.parquet as pq

    return pq.read_table(path, columns=columns)


def read_csv_file(path: str, **kw):
    import pandas as pd

    return pd.read_csv(path, **kw)


def read_json_file(path: str, lines: bool = True):
    import pandas as pd

    return pd.read_json(path, lines=lines)


def read_text_file(path: str, encoding: str = "utf-8",
                   drop_empty_lines: bool = True) -> List[str]:
    with open(path, "r", encoding=encoding) as f:
        lines = f.read().splitlines()
    return [l for l in lines if l or not drop_empty_lines]


def read_numpy_file(path: str) -> Dict[str, np.ndarray]:
    arr = np.load(path, allow_pickle=False)
    if isinstance(arr, np.lib.npyio.NpzFile):
        return {k: arr[k] for k in arr.files}
    return {"item": arr}


def read_binary_file(path: str, include_paths: bool = False):
    with open(path, "rb") as f:
        data = f.read()
    if include_paths:
        return [{"path": path, "bytes": data}]
    return [data]


# --------------------------------------------------------------- tfrecords #
# TFRecord framing (no TF dependency): each record is
#   [8B LE length][4B masked crc32c(length)][data][4B masked crc32c(data)]
# crc32c implemented table-driven (Castagnoli polynomial), mask per the
# TFRecord spec, so files round-trip with TensorFlow's readers.

_CRC32C_TABLE = None


def _crc32c(data: bytes) -> int:
    try:  # native implementations when present — the Python loop is slow
        import google_crc32c

        return int.from_bytes(google_crc32c.Checksum(data).digest(), "big")
    except ImportError:
        pass
    try:
        import crc32c as _c32

        return _c32.crc32c(data)
    except ImportError:
        pass
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ 0x82F63B78 if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


def read_tfrecord_file(path: str, validate: bool = True) -> List[Dict[str, Any]]:
    import struct

    rows: List[Dict[str, Any]] = []
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                break
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if validate and _masked_crc(header[:8]) != len_crc:
                raise ValueError(f"corrupt tfrecord length crc in {path}")
            data = f.read(length)
            footer = f.read(4)
            if len(data) < length or len(footer) < 4:
                raise ValueError(f"truncated tfrecord in {path}")
            (data_crc,) = struct.unpack("<I", footer)
            if validate and _masked_crc(data) != data_crc:
                raise ValueError(f"corrupt tfrecord data crc in {path}")
            rows.append({"data": data})
    return rows


def write_tfrecords(records, path: str) -> str:
    import struct

    with open(path, "wb") as f:
        for rec in records:
            data = rec["data"] if isinstance(rec, dict) else bytes(rec)
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc(data)))
    return path


# --------------------------------------------------------------------- sql #


def read_sql_query(sql: str, connection_factory, params=()) -> Dict[str, np.ndarray]:
    """Run one query through a DB-API connection factory (reference
    `ray.data.read_sql`); returns a columnar block."""
    conn = connection_factory()
    try:
        cur = conn.cursor()
        cur.execute(sql, params)
        cols = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        conn.close()
    if not rows:
        return {c: np.array([]) for c in cols}
    arrays = [np.array([r[i] for r in rows]) for i in range(len(cols))]
    return dict(zip(cols, arrays))


# ------------------------------------------------------------------- images #


def read_image_file(path: str, size=None, mode: Optional[str] = None
                    ) -> List[Dict[str, Any]]:
    """Decode one image to a numpy row (reference `ray.data.read_images`)."""
    from PIL import Image

    with Image.open(path) as img:
        if mode is not None:
            img = img.convert(mode)
        if size is not None:
            img = img.resize(tuple(size))
        arr = np.asarray(img)
    return [{"image": arr, "path": path}]


def read_webdataset_shard(path: str, decode: bool = True
                          ) -> List[Dict[str, Any]]:
    """One WebDataset tar shard -> sample rows (reference
    `python/ray/data/read_api.py` read_webdataset / the webdataset
    format: files sharing a basename stem form one sample; extensions
    become fields). Standard tarfile only — no webdataset dependency."""
    import io
    import json as _json
    import tarfile

    samples: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    with tarfile.open(path, "r:*") as tf:
        for member in tf:
            if not member.isfile():
                continue
            name = os.path.basename(member.name)
            if name.startswith("."):
                continue
            stem, _, ext = name.partition(".")
            raw = tf.extractfile(member).read()
            value: Any = raw
            if decode:
                if ext in ("txt", "text"):
                    value = raw.decode("utf-8", "replace")
                elif ext == "cls":
                    value = int(raw.decode().strip())
                elif ext == "json":
                    value = _json.loads(raw)
                elif ext in ("jpg", "jpeg", "png", "webp"):
                    try:
                        from PIL import Image

                        value = np.asarray(Image.open(io.BytesIO(raw)))
                    except Exception:  # noqa: BLE001 — no PIL: raw bytes
                        value = raw
            if stem not in samples:
                samples[stem] = {"__key__": stem}
                order.append(stem)
            samples[stem][ext] = value
    return [samples[k] for k in order]


def write_webdataset_shard(rows: List[Dict[str, Any]], path: str) -> str:
    """Rows ({'__key__': ..., ext: value}) -> one tar shard."""
    import io
    import json as _json
    import tarfile

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with tarfile.open(path, "w") as tf:
        for i, row in enumerate(rows):
            key = str(row.get("__key__", f"{i:08d}"))
            for ext, value in row.items():
                if ext == "__key__":
                    continue
                if isinstance(value, np.generic):
                    value = value.item()  # np scalar -> plain python
                if isinstance(value, bool):
                    value = int(value)  # .cls reads back via int()
                if isinstance(value, (bytes, bytearray)):
                    raw = bytes(value)
                elif isinstance(value, str):
                    raw = value.encode()
                elif isinstance(value, int):
                    raw = str(value).encode()
                else:
                    raw = _json.dumps(
                        value.tolist() if isinstance(value, np.ndarray)
                        else value).encode()
                info = tarfile.TarInfo(f"{key}.{ext}")
                info.size = len(raw)
                tf.addfile(info, io.BytesIO(raw))
    return path


def read_mongo_collection(uri: str, database: str, collection: str,
                          pipeline=None) -> List[Dict[str, Any]]:
    """MongoDB collection -> rows (reference MongoDatasource). Requires
    pymongo (not bundled; a clear error gates it)."""
    try:
        import pymongo
    except ImportError as e:
        raise ImportError(
            "read_mongo requires the pymongo package, which is not "
            "installed in this environment") from e
    client = pymongo.MongoClient(uri)
    try:
        coll = client[database][collection]
        cursor = coll.aggregate(pipeline) if pipeline else coll.find()
        return [{k: v for k, v in doc.items()} for doc in cursor]
    finally:
        client.close()


def make_range_block(start: int, stop: int) -> Dict[str, np.ndarray]:
    return {"id": np.arange(start, stop, dtype=np.int64)}


def make_tensor_range_block(start: int, stop: int, shape) -> Dict[str, np.ndarray]:
    n = stop - start
    base = np.arange(start, stop, dtype=np.float64).reshape((n,) + (1,) * len(shape))
    return {"data": np.broadcast_to(base, (n,) + tuple(shape)).copy()}


# ------------------------------------------------------------------ writers #


def write_block(block: Any, path: str, index: int, fmt: str,
                kw: Dict[str, Any]) -> str:
    from ray_tpu.data.block import BlockAccessor

    acc = BlockAccessor(block)
    out = os.path.join(path, f"part-{index:05d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(acc.to_arrow(), out)
    elif fmt == "csv":
        acc.to_pandas().to_csv(out, index=False)
    elif fmt == "json":
        acc.to_pandas().to_json(out, orient="records", lines=True)
    elif fmt == "numpy":
        col = kw.get("column", "item")
        np.save(out, acc.to_batch()[col])
        out += ".npy"
    elif fmt == "webdataset":
        out = os.path.join(path, f"shard-{index:06d}.tar")
        write_webdataset_shard(list(acc.rows()), out)
    else:
        raise ValueError(f"unknown write format {fmt}")
    return out
