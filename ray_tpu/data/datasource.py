"""Datasources: file readers/writers run inside read tasks.

Equivalent of the reference's `python/ray/data/datasource/*_datasource.py`
(parquet, csv, json, text, numpy, binary) + `file_based_datasource.py` path
expansion. Each reader returns one block per file chunk; the read happens in
the task, so bytes never flow through the driver.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List, Optional

import numpy as np


def expand_paths(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                f for f in glob.glob(os.path.join(p, "**", "*"), recursive=True)
                if os.path.isfile(f) and not os.path.basename(f).startswith((".", "_"))))
        elif any(c in p for c in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"No input files found for {paths}")
    return out


# ------------------------------------------------------------------ readers #


def read_parquet_file(path: str, columns: Optional[List[str]] = None):
    import pyarrow.parquet as pq

    return pq.read_table(path, columns=columns)


def read_csv_file(path: str, **kw):
    import pandas as pd

    return pd.read_csv(path, **kw)


def read_json_file(path: str, lines: bool = True):
    import pandas as pd

    return pd.read_json(path, lines=lines)


def read_text_file(path: str, encoding: str = "utf-8",
                   drop_empty_lines: bool = True) -> List[str]:
    with open(path, "r", encoding=encoding) as f:
        lines = f.read().splitlines()
    return [l for l in lines if l or not drop_empty_lines]


def read_numpy_file(path: str) -> Dict[str, np.ndarray]:
    arr = np.load(path, allow_pickle=False)
    if isinstance(arr, np.lib.npyio.NpzFile):
        return {k: arr[k] for k in arr.files}
    return {"item": arr}


def read_binary_file(path: str, include_paths: bool = False):
    with open(path, "rb") as f:
        data = f.read()
    if include_paths:
        return [{"path": path, "bytes": data}]
    return [data]


def make_range_block(start: int, stop: int) -> Dict[str, np.ndarray]:
    return {"id": np.arange(start, stop, dtype=np.int64)}


def make_tensor_range_block(start: int, stop: int, shape) -> Dict[str, np.ndarray]:
    n = stop - start
    base = np.arange(start, stop, dtype=np.float64).reshape((n,) + (1,) * len(shape))
    return {"data": np.broadcast_to(base, (n,) + tuple(shape)).copy()}


# ------------------------------------------------------------------ writers #


def write_block(block: Any, path: str, index: int, fmt: str,
                kw: Dict[str, Any]) -> str:
    from ray_tpu.data.block import BlockAccessor

    acc = BlockAccessor(block)
    out = os.path.join(path, f"part-{index:05d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(acc.to_arrow(), out)
    elif fmt == "csv":
        acc.to_pandas().to_csv(out, index=False)
    elif fmt == "json":
        acc.to_pandas().to_json(out, orient="records", lines=True)
    elif fmt == "numpy":
        col = kw.get("column", "item")
        np.save(out, acc.to_batch()[col])
        out += ".npy"
    else:
        raise ValueError(f"unknown write format {fmt}")
    return out
