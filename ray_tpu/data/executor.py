"""Streaming executor: pull-based block pipeline with backpressure.

Equivalent of the reference's `StreamingExecutor`
(`python/ray/data/_internal/execution/streaming_executor.py:48` and the
control loop in `streaming_executor_state.py:259-364`), redesigned around
this framework's one-hop task dispatch:

- consecutive 1:1 block transforms are FUSED into one remote call per block
  (the reference's operator fusion rule), so a read->map->filter pipeline
  costs one task per block;
- at most `max_tasks_in_flight_per_op` tasks run concurrently and at most
  `max_buffered_blocks_per_op` finished blocks sit unconsumed — the pump
  stops submitting until the consumer drains them (backpressure);
- blocks are yielded as ObjectRefs in SUBMISSION order (streaming, like
  the reference's ordered bundles): consumers start before the read
  finishes and iteration order is deterministic.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)


def _fused_apply(fns, producer, *args):
    """Remote body: run the producer (read task or identity on a block),
    then thread the block through every fused transform."""
    block = producer(*args) if producer is not None else args[0]
    for fn in fns:
        block = fn(block)
    return block


def _fused_apply_stats(fns, collector, producer, *args):
    """Stats-collecting remote body: same as _fused_apply, plus one
    fire-and-forget per-op timing record to the collector actor."""
    from ray_tpu.data.stats import timed_apply

    block, records = timed_apply(fns, producer, args)
    try:
        collector.record.remote(records)
    except Exception:  # noqa: BLE001 — stats must never fail the block
        pass
    return block


class StreamingExecutor:
    """Pumps (producer, args) work items through fused transforms."""

    def __init__(self, transforms: List[Callable],
                 max_in_flight: Optional[int] = None,
                 max_buffered: Optional[int] = None,
                 resources: Optional[dict] = None,
                 stats_collector: Optional[Any] = None):
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        self._transforms = transforms
        self._max_in_flight = max_in_flight or ctx.max_tasks_in_flight_per_op
        self._max_buffered = max_buffered or ctx.max_buffered_blocks_per_op
        self._resources = resources
        self._stats = stats_collector

    def execute(self, work: Iterator[Tuple[Optional[Callable], tuple]]
                ) -> Iterator[Any]:
        """work: iterator of (producer, args). Yields block ObjectRefs in
        submission order (streaming)."""
        import ray_tpu

        if self._stats is not None:
            base = ray_tpu.remote(_fused_apply_stats)
            extra = (self._stats.actor,)
        else:
            base = ray_tpu.remote(_fused_apply)
            extra = ()
        remote_fn = base.options(**self._resources) if self._resources \
            else base

        work_iter = iter(work)
        in_flight: dict = {}          # ref -> submission index
        buffered: dict = {}           # submission index -> ready ref
        submitted = 0
        emit = 0                      # next index to yield (ordered)
        exhausted = False
        while True:
            # Submit while under the in-flight cap and backpressure allows.
            while (not exhausted and len(in_flight) < self._max_in_flight
                   and len(buffered) + len(in_flight) < self._max_buffered):
                try:
                    producer, args = next(work_iter)
                except StopIteration:
                    exhausted = True
                    break
                ref = remote_fn.remote(self._transforms, *extra,
                                       producer, *args)
                in_flight[ref] = submitted
                submitted += 1
            # Yield strictly in submission order (the reference's streaming
            # executor preserves block order): later-finished blocks buffer
            # until their predecessors emit — iteration is deterministic.
            if emit in buffered:
                yield buffered.pop(emit)
                emit += 1
                continue
            if not in_flight:
                if exhausted and not buffered:
                    return
                if not buffered:
                    continue
            ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                    timeout=10.0)
            for r in ready:
                buffered[in_flight.pop(r)] = r


def apply_transforms_local(transforms: List[Callable], block: Any) -> Any:
    for fn in transforms:
        block = fn(block)
    return block
