"""Streaming executor: pull-based block pipeline with byte-budget backpressure.

Equivalent of the reference's `StreamingExecutor`
(`python/ray/data/_internal/execution/streaming_executor.py:48` and the
control loop in `streaming_executor_state.py:259-364`), redesigned around
this framework's one-hop task dispatch:

- consecutive 1:1 block transforms are FUSED into one remote call per block
  (the reference's operator fusion rule), so a read->map->filter pipeline
  costs one task per block;
- at most `max_tasks_in_flight_per_op` tasks run concurrently, and the
  pipeline's in-flight OUTPUT is bounded in BYTES, not blocks: every
  submission charges the execution's ByteBudget with the op's moving size
  estimate (corrected to the sealed size once the directory knows it) and
  the pump stalls while the pipeline is over budget — see
  ray_tpu/data/streaming/budget.py for the budget model and the per-op
  backpressure accounting surfaced by `ds.stats()`;
- blocks are yielded as ObjectRefs in SUBMISSION order (streaming, like
  the reference's ordered bundles): consumers start before the read
  finishes and iteration order is deterministic;
- each submitted block records its lineage recipe (producer, args, fused
  transforms), so a lost block recomputes instead of failing the pipeline
  (streaming/lineage.py; ref-valued args stay pinned until delivery —
  that is the recovery window).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)


def _fused_apply(fns, producer, *args):
    """Remote body: run the producer (read task or identity on a block),
    then thread the block through every fused transform."""
    block = producer(*args) if producer is not None else args[0]
    for fn in fns:
        block = fn(block)
    return block


def _fused_apply_stats(fns, collector, producer, *args):
    """Stats-collecting remote body: same as _fused_apply, plus one
    fire-and-forget per-op timing record to the collector actor (whose
    keyed state is bounded — see data/stats.py)."""
    from ray_tpu.data.stats import timed_apply

    block, records = timed_apply(fns, producer, args)
    try:
        collector.record.remote(records)
    except Exception:  # noqa: BLE001 — stats must never fail the block
        pass
    return block


class StreamingExecutor:
    """Pumps (producer, args) work items through fused transforms."""

    def __init__(self, transforms: List[Callable],
                 max_in_flight: Optional[int] = None,
                 max_buffered: Optional[int] = None,
                 resources: Optional[dict] = None,
                 stats_collector: Optional[Any] = None,
                 lineage: Optional[Any] = None,
                 op_name: Optional[str] = None):
        from ray_tpu.data.context import DataContext

        ctx = DataContext.get_current()
        self._transforms = transforms
        self._max_in_flight = max_in_flight or ctx.max_tasks_in_flight_per_op
        self._max_buffered = max_buffered or ctx.max_buffered_blocks_per_op
        self._resources = resources
        self._stats = stats_collector
        self._lineage = lineage
        from ray_tpu.data.streaming.budget import unique_op

        self._op = unique_op(op_name or (
            "+".join(getattr(fn, "_op_name", None)
                     or getattr(fn, "__name__", "fn")
                     for fn in transforms) if transforms else "Read"))
        self._est_bytes = float(ctx.target_min_block_size)
        self.last_budget_stats: Optional[dict] = None

    def _observe_size(self, budget, charged: int, ref) -> int:
        """Correct the in-flight charge to the sealed size and feed the
        op's size estimate (EMA) for future admissions."""
        from ray_tpu.data.streaming.shuffle import _block_size

        actual = _block_size(ref)
        if actual is None:
            return charged
        self._est_bytes = 0.8 * self._est_bytes + 0.2 * actual
        budget.adjust(self._op, actual - charged)
        return actual

    def execute(self, work: Iterator[Tuple[Optional[Callable], tuple]]
                ) -> Iterator[Any]:
        """work: iterator of (producer, args). Yields block ObjectRefs in
        submission order (streaming)."""
        import ray_tpu
        from ray_tpu.data.streaming.budget import pipeline_budget

        if self._stats is not None:
            base = ray_tpu.remote(_fused_apply_stats)
            extra = (self._stats.actor,)
        else:
            base = ray_tpu.remote(_fused_apply)
            extra = ()
        remote_fn = base.options(**self._resources) if self._resources \
            else base

        with pipeline_budget() as budget:
            try:
                yield from self._pump(budget, remote_fn, extra, iter(work))
            finally:
                budget.release_op(self._op)
                self.last_budget_stats = budget.stats()

    def _pump(self, budget, remote_fn, extra, work_iter) -> Iterator[Any]:
        import time as _time

        import ray_tpu

        in_flight: dict = {}          # ref -> (submission index, charge)
        buffered: dict = {}           # submission index -> (ref, charge)
        submitted = 0
        emit = 0                      # next index to yield (ordered)
        exhausted = False
        blocked_since: Optional[float] = None
        pending: Optional[tuple] = None  # work item awaiting admission
        while True:
            # Submit while under the task cap; the byte budget is the
            # primary backpressure. try_acquire + drain-on-refusal: a
            # blocking acquire here would deadlock the single-threaded
            # pump (its own yield path is what releases charges).
            while (not exhausted and len(in_flight) < self._max_in_flight
                   and len(buffered) + len(in_flight) < self._max_buffered):
                if pending is None:
                    try:
                        pending = next(work_iter)
                    except StopIteration:
                        exhausted = True
                        break
                charge = int(self._est_bytes)
                if not budget.try_acquire(self._op, charge):
                    if blocked_since is None:
                        blocked_since = _time.perf_counter()
                    break  # over budget: drain/yield below, retry after
                if blocked_since is not None:
                    budget.note_blocked(
                        self._op, _time.perf_counter() - blocked_since)
                    blocked_since = None
                producer, args = pending
                pending = None
                ref = remote_fn.remote(self._transforms, *extra,
                                       producer, *args)
                if self._lineage is not None:
                    # Ref-valued args stay pinned by the recipe until the
                    # block is delivered (resolve() forgets on success) —
                    # the recovery window for a dependency dying under a
                    # "completed" task.
                    self._lineage.record(ref, producer, args,
                                         self._transforms)
                in_flight[ref] = (submitted, charge)
                submitted += 1
            # Yield strictly in submission order (the reference's streaming
            # executor preserves block order): later-finished blocks buffer
            # until their predecessors emit — iteration is deterministic.
            if emit in buffered:
                ref, charge = buffered.pop(emit)
                budget.release(self._op, charge)
                yield ref
                emit += 1
                continue
            if not in_flight:
                if exhausted and not buffered:
                    return
                if not buffered:
                    continue
            ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                    timeout=10.0)
            for r in ready:
                idx, charge = in_flight.pop(r)
                buffered[idx] = (r, self._observe_size(budget, charge, r))


def apply_transforms_local(transforms: List[Callable], block: Any) -> Any:
    for fn in transforms:
        block = fn(block)
    return block
