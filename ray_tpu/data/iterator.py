"""Data iterators: batching, prefetch, and coordinated streaming splits.

Equivalent of the reference's `DataIterator` (`python/ray/data/iterator.py`),
the prefetching batcher (`_internal/block_batching/iter_batches.py`) and
`StreamSplitDataIterator` (`_internal/iterator/stream_split_iterator.py:41`):
`streaming_split(n)` starts ONE coordinator actor that drives a single
streaming execution and hands blocks to whichever consumer asks first, so
fast train workers pull more data instead of idling on a static shard.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.data.block import BlockAccessor


def batch_blocks(blocks: Iterator[Any], batch_size: int,
                 drop_last: bool = False) -> Iterator[Dict[str, np.ndarray]]:
    """Re-chunk a stream of blocks into exact-size numpy-dict batches."""
    carry: Optional[Dict[str, np.ndarray]] = None
    for block in blocks:
        batch = BlockAccessor(block).to_batch()
        if not batch or len(next(iter(batch.values()))) == 0:
            continue
        if carry is not None:
            batch = {k: np.concatenate([carry[k], batch[k]]) for k in batch}
            carry = None
        n = len(next(iter(batch.values())))
        start = 0
        while n - start >= batch_size:
            yield {k: v[start:start + batch_size] for k, v in batch.items()}
            start += batch_size
        if start < n:
            carry = {k: v[start:] for k, v in batch.items()}
    if carry is not None and not drop_last:
        yield carry


class DataIterator:
    """Per-consumer view of a Dataset (whole dataset, no split)."""

    def __init__(self, dataset):
        self._dataset = dataset

    def iter_batches(self, *, batch_size: int = 256, drop_last: bool = False,
                     prefetch_batches: int = 1
                     ) -> Iterator[Dict[str, np.ndarray]]:
        yield from batch_blocks(self._dataset._iter_block_values(),
                                batch_size, drop_last)

    def iter_rows(self) -> Iterator[Any]:
        yield from self._dataset.iter_rows()

    def iter_shards(self, n: int, *, prefetch: Optional[int] = None,
                    equal: bool = False) -> List["Any"]:
        """n coordinated per-host shards over ONE shared streaming
        execution, each double-buffer-prefetching `prefetch` blocks
        (default `data_prefetch_shards`) ahead of its consumer with
        step-stall accounting — the train ingest path (see
        ray_tpu/data/streaming/ingest.py)."""
        from ray_tpu.data.streaming.ingest import iter_shards

        return iter_shards(self._dataset, n, prefetch=prefetch, equal=equal)

    def materialize(self):
        return self._dataset.materialize()


class _SplitCoordinator:
    """Actor driving one streaming execution for n consumers.

    Blocks are handed out first-come-first-served; `equal` slices each block
    so no consumer can run ahead by more than one block.

    Locality-aware handout: the coordinator keeps a small lookahead of
    produced refs and, when a consumer identifies its node, prefers a
    ref ALREADY RESIDENT there (one batched directory RPC over the
    lookahead — the consumer's pull then reads local shared memory
    instead of the wire). Any consumer still receives SOME block on
    every call — locality reorders the handout, it never starves a
    split — and every block is handed out exactly once.
    """

    _LOOKAHEAD = 4

    def __init__(self, ds_blob: bytes, n: int, equal: bool):
        import cloudpickle

        self._ds = cloudpickle.loads(ds_blob)
        self._n = n
        self._equal = equal
        self._epoch = -1
        self._iter: Optional[Iterator[Any]] = None
        self._ahead: List[Any] = []
        self._locality = {"locality_hits": 0, "locality_misses": 0}
        self._lock = threading.Lock()

    def _pick_local(self, node_hex: Optional[str]):
        """(lookahead index, is_local) of a block resident on the
        consumer's node; (0, False) — FIFO head, counted as a miss —
        when nothing is local or locations are unknown. The routing
        knob is NOT re-checked here: it resolves on the CONSUMER
        (see StreamSplitDataIterator._iter_blocks — a consumer with
        routing off advertises no node), because this actor may run in
        a reused worker process whose DataContext carries another
        consumer's override. One batched directory RPC over the whole
        lookahead."""
        if not node_hex or not self._ahead:
            return 0, False
        from ray_tpu.data.query import locality

        for i, entry in enumerate(locality.locations_batch(self._ahead)):
            if entry.get("known") and node_hex in (entry.get("nodes") or ()):
                return i, True
        return 0, False

    def next_block(self, split_id: int, epoch: int,
                   node_hex: Optional[str] = None) -> Dict[str, Any]:
        with self._lock:
            if epoch > self._epoch:
                self._epoch = epoch
                # Hand out REFS, not values: the consumer pulls the block
                # to ITS host over the transfer plane's location-aware
                # pipelined pull (locality routing), instead of every
                # block transiting this actor's response path by value.
                self._iter = self._ds._iter_block_refs()
                self._ahead = []
            if epoch < self._epoch or self._iter is None:
                return {"end": True}
            while len(self._ahead) < self._LOOKAHEAD:
                try:
                    self._ahead.append(next(self._iter))
                except StopIteration:
                    break
            if not self._ahead:
                return {"end": True}
            idx, local = self._pick_local(node_hex)
            self._locality[
                "locality_hits" if local else "locality_misses"] += 1
            return {"ref": self._ahead.pop(idx), "local": local}

    def stats(self) -> Dict[str, Any]:
        return {"epoch": self._epoch, "n": self._n, **self._locality}


class StreamSplitDataIterator:
    """One of n coordinated consumers; picklable (ships to train workers)."""

    def __init__(self, coordinator, split_id: int, n: int):
        self._coordinator = coordinator
        self._split_id = split_id
        self._n = n
        self._epoch = 0
        # This consumer's view of the coordinator's routing decisions:
        # a hit = the handed block was already resident on this node
        # (the pull below reads shared memory, not the wire).
        self._locality = {"locality_hits": 0, "locality_misses": 0}

    def locality_stats(self) -> Dict[str, int]:
        return dict(self._locality)

    def iter_batches(self, *, batch_size: int = 256, drop_last: bool = False,
                     prefetch_batches: int = 1
                     ) -> Iterator[Dict[str, np.ndarray]]:
        yield from batch_blocks(self._iter_blocks(), batch_size, drop_last)

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_blocks():
            yield from BlockAccessor(block).rows()

    def _iter_blocks(self) -> Iterator[Any]:
        import ray_tpu
        from ray_tpu.data.query import locality

        epoch = self._epoch
        self._epoch += 1
        # Identify this node ONCE per epoch; the coordinator then hands
        # this consumer blocks already resident here when it can. The
        # routing knob is resolved HERE (consumer side) — the
        # coordinator may run in another process whose DataContext never
        # saw a driver-side override; not advertising a node disables
        # routing for this consumer regardless of where the coordinator
        # lives.
        from ray_tpu.data.context import DataContext

        node_hex = (locality.local_node_hex()
                    if DataContext.get_current().resolved_locality_routing()
                    else None)
        while True:
            resp = ray_tpu.get(
                self._coordinator.next_block.remote(self._split_id, epoch,
                                                    node_hex))
            if resp.get("end"):
                return
            if "ref" in resp:
                self._locality[
                    "locality_hits" if resp.get("local")
                    else "locality_misses"] += 1
                # Locality pull: materialize on THIS host via the
                # transfer plane (chunked, striped across holders) — a
                # hit short-circuits to a local shared-memory read.
                yield ray_tpu.get(resp["ref"])
            else:
                yield resp["block"]

    def __reduce__(self):
        return (StreamSplitDataIterator,
                (self._coordinator, self._split_id, self._n))


def make_streaming_splits(dataset, n: int, equal: bool = False
                          ) -> List[StreamSplitDataIterator]:
    import cloudpickle

    import ray_tpu

    blob = cloudpickle.dumps(dataset)
    coordinator = ray_tpu.remote(_SplitCoordinator).options(
        max_concurrency=max(2, n)).remote(blob, n, equal)
    return [StreamSplitDataIterator(coordinator, i, n) for i in range(n)]
