"""Distributed query tier: width-scale exchange operators over the
streaming plane.

Three operators — range-partitioned sort, hash-aggregate groupby, and a
broadcast/shuffle join — run as budget-bounded dataflows through the
PR-13 windowed-shuffle machinery (ray_tpu/data/streaming/shuffle.py):
rows never transit the driver (the sort's boundary sample is the one
bounded exception), intermediates seal into the spillable store, every
partition carries a `BlockLineage` recipe for bounded mid-shuffle
recovery, and per-op backpressure lands in `ds.stats()`. Consumption is
locality-routed (query/locality.py): reduce tasks NodeAffinity-place on
bucket holders, and same-host handoff rides the raylet's sealed-segment
shm attach instead of a socket copy.

See docs/DATA_QUERY.md for operator semantics and knobs.
"""

from ray_tpu.data.query.aggregate import (
    AggregateFn,
    Count,
    Max,
    Mean,
    Min,
    Sum,
)
from ray_tpu.data.query.join import join_datasets
from ray_tpu.data.query.sort import sort_dataset

__all__ = [
    "AggregateFn",
    "Count",
    "Sum",
    "Min",
    "Max",
    "Mean",
    "join_datasets",
    "sort_dataset",
]
