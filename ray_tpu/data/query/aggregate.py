"""Hash-aggregate groupby: partial pre-aggregation, hash scatter, merge.

The seed-era GroupedData pulled EVERY block to the driver and merged
partials in-process — the exact driver-materialization this tier exists
to kill (and that raylint RL019 now flags). The distributed plan:

1. **Partial aggregate** (fused map): each block collapses to at most
   one partial row per distinct key — ``{"k": key, "s": [state, ...]}``
   — before anything moves. Columnar dict-of-arrays blocks take a
   vectorized path (np.unique + bincount/reduceat) so multi-GB blocks
   never iterate rows in Python.
2. **Hash scatter**: partial rows exchange through the windowed shuffle
   (mode="hash" on "k"), so every key's partials co-locate on one
   reducer. Budget, spill, lineage, and backpressure all inherit from
   the shuffle — a groupby whose key cardinality exceeds memory spills,
   it does not OOM.
3. **Merge + finalize** (fused on reduce outputs): states merge per key
   and finalize into result rows named by each AggregateFn ("count()",
   "sum(v)", ...).
4. **Global order**: results range-sort by key through the distributed
   sort (lenient — unorderable mixed keys degrade to unsorted, matching
   the seed contract's TypeError tolerance).

States are tiny scalars/tuples, so stages 2-4 move kilobytes even when
stage 1 read gigabytes — the whole point of pre-aggregation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


class AggregateFn:
    """Composable aggregation: init() -> state, accumulate(state, row),
    merge(state, state), finalize(state) -> value under `name` in the
    result row. `vectorize(block, inv, n_groups)` optionally returns a
    per-group state list for a columnar block (None = fall back to the
    row path for that block)."""

    def __init__(self, init: Callable[[], Any],
                 accumulate: Callable[[Any, Any], Any],
                 merge: Callable[[Any, Any], Any],
                 finalize: Optional[Callable[[Any], Any]] = None,
                 name: str = "agg()",
                 vectorize: Optional[Callable] = None):
        self.init = init
        self.accumulate = accumulate
        self.merge = merge
        self.finalize = finalize or (lambda s: s)
        self.name = name
        self.vectorize = vectorize


def _group_reduce(vals: np.ndarray, inv: np.ndarray, n_groups: int,
                  ufunc) -> Optional[list]:
    """Per-group ufunc.reduceat over values stable-sorted by group id;
    keeps the column dtype (int sums stay ints). None for object/empty
    groups edge cases the caller should row-path instead."""
    if vals.dtype == object:
        return None
    order = np.argsort(inv, kind="stable")
    starts = np.searchsorted(inv[order], np.arange(n_groups))
    return [v.item() for v in ufunc.reduceat(vals[order], starts)]


def _col(block, on) -> Optional[np.ndarray]:
    if on not in block:
        return None
    vals = np.asarray(block[on])
    return None if vals.dtype == object else vals


def Count() -> AggregateFn:
    return AggregateFn(
        lambda: 0, lambda s, r: s + 1, lambda a, b: a + b,
        name="count()",
        vectorize=lambda block, inv, n:
            np.bincount(inv, minlength=n).tolist())


def Sum(on: str) -> AggregateFn:
    def acc(s, r):
        v = r.get(on) if isinstance(r, dict) else r
        if v is None:
            return s
        return v if s is None else s + v

    def merge(a, b):
        if a is None:
            return b
        return a if b is None else a + b

    def vec(block, inv, n):
        vals = _col(block, on)
        return None if vals is None else _group_reduce(vals, inv, n, np.add)

    return AggregateFn(lambda: None, acc, merge, name=f"sum({on})",
                       vectorize=vec)


def Min(on: str) -> AggregateFn:
    def acc(s, r):
        v = r.get(on) if isinstance(r, dict) else r
        if v is None:
            return s
        return v if s is None else min(s, v)

    def merge(a, b):
        if a is None:
            return b
        return a if b is None else min(a, b)

    def vec(block, inv, n):
        vals = _col(block, on)
        return None if vals is None else _group_reduce(vals, inv, n,
                                                       np.minimum)

    return AggregateFn(lambda: None, acc, merge, name=f"min({on})",
                       vectorize=vec)


def Max(on: str) -> AggregateFn:
    def acc(s, r):
        v = r.get(on) if isinstance(r, dict) else r
        if v is None:
            return s
        return v if s is None else max(s, v)

    def merge(a, b):
        if a is None:
            return b
        return a if b is None else max(a, b)

    def vec(block, inv, n):
        vals = _col(block, on)
        return None if vals is None else _group_reduce(vals, inv, n,
                                                       np.maximum)

    return AggregateFn(lambda: None, acc, merge, name=f"max({on})",
                       vectorize=vec)


def Mean(on: str) -> AggregateFn:
    """State (total, n) counts only non-None values — mean of all-None
    is None, matching the seed semantics."""

    def acc(s, r):
        v = r.get(on) if isinstance(r, dict) else r
        if v is None:
            return s
        return (s[0] + v, s[1] + 1)

    def merge(a, b):
        return (a[0] + b[0], a[1] + b[1])

    def fin(s):
        return s[0] / s[1] if s[1] else None

    def vec(block, inv, n):
        vals = _col(block, on)
        if vals is None:
            return None
        totals = _group_reduce(vals.astype(np.float64), inv, n, np.add)
        if totals is None:
            return None
        counts = np.bincount(inv, minlength=n)
        return list(zip(totals, counts.tolist()))

    return AggregateFn(lambda: (0.0, 0), acc, merge, fin,
                       name=f"mean({on})", vectorize=vec)


def _partial_key(row):
    return row["k"]


def make_partial_transform(key, aggs: List[AggregateFn]) -> Callable:
    """Fused map transform: block -> list of partial rows, one per
    distinct key seen in this block."""

    def _key_of(row):
        if callable(key):
            return key(row)
        return row[key]

    def transform(block):
        from ray_tpu.data.block import BlockAccessor, _is_batch_dict

        if (_is_batch_dict(block) and block and isinstance(key, str)
                and all(a.vectorize is not None for a in aggs)):
            col = np.asarray(block[key])
            if col.dtype != object:
                uk, inv = np.unique(col, return_inverse=True)
                per_agg = [a.vectorize(block, inv, len(uk)) for a in aggs]
                if all(s is not None for s in per_agg):
                    return [{"k": uk[g].item(),
                             "s": [sa[g] for sa in per_agg]}
                            for g in range(len(uk))]
        acc_by_key: Dict[Any, list] = {}
        for row in BlockAccessor(block).rows():
            k = _key_of(row)
            if hasattr(k, "item"):
                k = k.item()
            states = acc_by_key.get(k)
            if states is None:
                states = acc_by_key[k] = [a.init() for a in aggs]
            for i, a in enumerate(aggs):
                states[i] = a.accumulate(states[i], row)
        return [{"k": k, "s": states} for k, states in acc_by_key.items()]

    transform._op_name = "PartialAggregate"
    return transform


def make_merge_transform(key_name: str, aggs: List[AggregateFn]) -> Callable:
    """Fused reduce transform: partial rows (one partition's worth,
    co-located by the hash scatter) -> finalized result rows."""

    def transform(block):
        from ray_tpu.data.block import BlockAccessor

        merged: Dict[Any, list] = {}
        for row in BlockAccessor(block).rows():
            k = row["k"]
            states = merged.get(k)
            if states is None:
                merged[k] = list(row["s"])
            else:
                for i, a in enumerate(aggs):
                    states[i] = a.merge(states[i], row["s"][i])
        return [dict([(key_name, k)]
                     + [(a.name, a.finalize(states[i]))
                        for i, a in enumerate(aggs)])
                for k, states in merged.items()]

    transform._op_name = "MergeAggregate"
    return transform


def grouped_aggregate(ds, key, key_name: str, aggs: List[AggregateFn]):
    """Full distributed groupby plan over `ds`; returns a lazy Dataset of
    result rows, globally sorted by key when keys are orderable."""
    from ray_tpu.data.query.sort import sort_dataset

    partials = ds._derive(make_partial_transform(key, aggs))
    shuffled = partials._push_shuffle(mode="hash", key_fn=_partial_key)
    merged = shuffled._derive(make_merge_transform(key_name, aggs))
    return sort_dataset(merged, key_name, False, lenient=True)
