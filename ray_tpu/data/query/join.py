"""Distributed join: broadcast when the build side is small, hash
exchange otherwise.

- **Broadcast join**: when the right (build) side's sealed bytes fit
  `query_broadcast_join_bytes`, every left block probes against ALL
  right blocks in one fused task whose args carry the right-side refs —
  the object store ships each right block to a node AT MOST ONCE (the
  store caches; with same-host attach the second consumer on a node
  pays a memcpy, not a socket). No exchange of the large side at all.
- **Hash-shuffle join**: both sides exchange through the windowed
  shuffle (mode="hash" on their join keys, SAME partition count), so
  partition i of the left can only match partition i of the right; a
  per-partition task builds a hash table from the right rows and probes
  left rows in order. Both exchanges share one pipeline ByteBudget, so
  a join never holds more unsealed bytes than any other dataflow.

Semantics (inner/left): left row order is preserved; each left row
emits one merged row per matching right row, in right-side original
order. Merged rows take left values; colliding non-key right columns
get the "_1" suffix (the zip() convention). `how="left"` emits
unmatched left rows with the right side's observed columns set to None.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

_HOW = ("inner", "left")


class _KeyGetter:
    """Picklable row -> join-key extractor for the hash exchange."""

    def __init__(self, on: str):
        self.on = on

    def __call__(self, row):
        if not isinstance(row, dict):
            raise ValueError(
                "join() needs record rows (dicts) with the join column; "
                f"got {type(row).__name__}")
        return row[self.on]


def _merge_row(lrow: dict, rrow: Optional[dict], left_on: str,
               right_on: str, rcols: List[str]) -> dict:
    out = dict(lrow)
    if rrow is None:  # left-join miss: observed right columns -> None
        for c in rcols:
            if c != right_on:
                out[c + "_1" if c in lrow else c] = None
        return out
    for c, v in rrow.items():
        if c == right_on:
            continue  # join key already present from the left row
        out[c + "_1" if c in lrow else c] = v
    return out


def right_block_columns(block) -> List[str]:
    """Column NAMES of one build-side block, in observation order —
    bounded metadata the driver unions so left-join None-fill agrees
    across strategies (a hash partition may see none/part of the right
    columns; the broadcast path always sees them all)."""
    from ray_tpu.data.block import BlockAccessor

    cols: List[str] = []
    seen = set()
    for row in BlockAccessor(block).rows():
        if isinstance(row, dict):
            for c in row:
                if c not in seen:
                    seen.add(c)
                    cols.append(c)
    return cols


def join_partition_blocks(left_on: str, right_on: str, how: str,
                          rcols_hint: Optional[List[str]],
                          left_block, *right_blocks):
    """Build a hash table from the right rows, probe left rows in order.
    Runs remotely — as the per-partition task of the shuffle join, or as
    the per-left-block task of the broadcast join (right_blocks then =
    the ENTIRE build side). `rcols_hint` carries the GLOBAL right-side
    column set for left joins on the hash path, where this partition's
    slice of the build side may not observe every column."""
    from ray_tpu.data.block import BlockAccessor

    build: Dict[Any, List[dict]] = {}
    rcols: List[str] = list(rcols_hint or ())
    seen_cols = set(rcols)
    for rb in right_blocks:
        for rrow in BlockAccessor(rb).rows():
            if not isinstance(rrow, dict):
                raise ValueError(
                    "join() needs record rows (dicts) with the join "
                    f"column; got {type(rrow).__name__}")
            k = rrow[right_on]
            if hasattr(k, "item"):
                k = k.item()
            build.setdefault(k, []).append(rrow)
            for c in rrow:
                if c not in seen_cols:
                    seen_cols.add(c)
                    rcols.append(c)
    out: List[dict] = []
    for lrow in BlockAccessor(left_block).rows():
        if not isinstance(lrow, dict):
            raise ValueError(
                "join() needs record rows (dicts) with the join column; "
                f"got {type(lrow).__name__}")
        k = lrow[left_on]
        if hasattr(k, "item"):
            k = k.item()
        matches = build.get(k)
        if matches:
            for rrow in matches:
                out.append(_merge_row(lrow, rrow, left_on, right_on, rcols))
        elif how == "left":
            out.append(_merge_row(lrow, None, left_on, right_on, rcols))
    return out


def resolve_on(on) -> Tuple[str, str]:
    if isinstance(on, str):
        return on, on
    if (isinstance(on, (tuple, list)) and len(on) == 2
            and all(isinstance(c, str) for c in on)):
        return on[0], on[1]
    raise ValueError("join(on=...) takes a column name or a "
                     "(left_col, right_col) pair")


def join_datasets(left, right, on, how: str = "inner"):
    """Lazy distributed join of two Datasets; strategy (broadcast vs
    hash exchange) is chosen at iteration time from the build side's
    actual sealed bytes. `last_join_stats` on the result records the
    decision."""
    from ray_tpu.data.dataset import _JoinDataset

    if how not in _HOW:
        raise ValueError(f"join(how=...) must be one of {_HOW}")
    left_on, right_on = resolve_on(on)
    return _JoinDataset(left, right, left_on, right_on, how)
