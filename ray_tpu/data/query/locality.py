"""Locality-routed consumption for the query tier.

The windowed shuffle's reduce tasks concat N bucket blocks scattered
across the cluster; left to the default policy they land wherever a
lease is warm and drag every bucket over the link model. This module
resolves bucket locations from the GCS object directory in ONE batch
RPC per partition and pins the reduce (softly) to the node already
holding the most bucket bytes — the task moves to the data, reference
`LocalityAwareLeasePolicy` (`lease_policy.h:56`), but for the data
plane's exchange operators instead of lease scoring.

Routing is advisory everywhere: a directory miss, a dead node, or the
`data_locality_routing` knob being off all degrade to the default
placement — never an error. Counters (`stats()`) record routed vs
fallback decisions so benches can A/B the cross-node byte savings.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_stats = {"routed": 0, "fallback": 0}


def reset_stats() -> None:
    with _lock:
        _stats["routed"] = 0
        _stats["fallback"] = 0


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


def _note(routed: bool) -> None:
    with _lock:
        _stats["routed" if routed else "fallback"] += 1


def _node_hex(node: Any) -> str:
    """Directory entries carry NodeID objects; every consumer here keys
    and compares by hex string (the form `local_node_hex` and
    `NodeAffinitySchedulingStrategy` speak)."""
    return node.hex() if hasattr(node, "hex") else str(node)


def locations_batch(refs: List[Any]) -> List[Dict[str, Any]]:
    """Directory entries (nodes + size, no payloads) for a list of
    ObjectRefs, one RPC; node ids normalized to hex strings. Empty on
    any failure — locality is advisory."""
    import ray_tpu

    runtime = getattr(ray_tpu, "_global_runtime", None)
    if runtime is None or not refs:
        return []
    try:
        resp = runtime.gcs.call(
            "object_locations_batch",
            {"object_ids": [r.object_id for r in refs]}, timeout=10)
        entries = resp.get("entries", [])
    except Exception:  # noqa: BLE001 — advisory, never fatal
        return []
    for entry in entries:
        entry["nodes"] = [_node_hex(n) for n in entry.get("nodes") or ()]
    return entries


def best_node_for(refs: List[Any]) -> Optional[str]:
    """Node hex holding the most resident bytes of `refs` (each holder
    has a full copy, so every listed node is charged the object's size).
    None when nothing is known — e.g. all blocks rode the GCS inline
    path and live nowhere in particular."""
    resident: Dict[str, int] = {}
    for entry in locations_batch(refs):
        if not entry.get("known"):
            continue
        size = int(entry.get("size") or 0)
        if size <= 0:
            continue
        for node_hex in entry.get("nodes", ()):
            resident[node_hex] = resident.get(node_hex, 0) + size
    if not resident:
        return None
    # Deterministic argmax (ties break by hex) so reruns route alike.
    return max(sorted(resident), key=lambda n: resident[n])


def reduce_affinity(refs: List[Any]) -> Optional[Dict[str, Any]]:
    """`.options()` kwargs pinning a reduce task (softly) onto the node
    holding most of its bucket bytes; None = no information, place by
    the default policy. Counts the decision either way."""
    node_hex = best_node_for(refs)
    if node_hex is None:
        _note(routed=False)
        return None
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    _note(routed=True)
    return {"scheduling_strategy":
            NodeAffinitySchedulingStrategy(node_hex, soft=True)}


def local_node_hex() -> Optional[str]:
    """This process's node, when known (driver and workers both carry
    it); None outside a cluster."""
    import ray_tpu

    runtime = getattr(ray_tpu, "_global_runtime", None)
    if runtime is None or runtime.node_id is None:
        return None
    return runtime.node_id.hex()


def block_is_local(ref: Any) -> bool:
    """Sealed copy already in THIS node's store (shared-memory read, no
    transfer at all)?"""
    import ray_tpu

    runtime = getattr(ray_tpu, "_global_runtime", None)
    if runtime is None:
        return False
    try:
        return runtime.store.contains(ref.object_id)
    except Exception:  # noqa: BLE001 — store mid-shutdown
        return False
