"""Distributed sort: sample-based range partitioning over the windowed
shuffle.

Plan (reference `push_based_shuffle.py` / the classic TeraSort shape):

1. **Sample.** One remote task per parent block draws at most
   ceil(sample_rows / n_blocks) keys (seeded, without replacement) and
   ships ONLY the keys back. The driver never sees rows — its resident
   footprint is bounded by `query_sort_sample_rows` keys, an invariant
   `last_sort_stats["driver_sample_bytes"]` makes assertable.
2. **Range scatter.** Sorted samples cut into n_parts-1 boundary keys; a
   `_RangePartitioner` (picklable, ships in the map closure) assigns
   row -> partition by bisect_right, so EQUAL KEYS NEVER SPLIT across
   partitions. The exchange itself is `iter_shuffled_refs(mode="keyed")`
   — windowed, budget-bounded, spillable, lineage-recorded: the sort
   inherits every recovery and backpressure property of the shuffle.
3. **Local sort.** Each partition stable-sorts locally (fused transform,
   never driver-side). Range partitioning preserves each block's
   original row order within a partition (buckets concat in block
   order), so stable local sort == exact stable global sort: output is
   row-identical to driver-side ``sorted(rows, key=...)`` REGARDLESS of
   which keys the sample happened to draw. Samples only steer balance,
   never correctness.

Descending flips the partition index (n_parts-1-idx) and runs a stable
descending local sort, preserving original order among equal keys — the
same contract as ``sorted(..., reverse=True)``.
"""

from __future__ import annotations

import bisect
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np

_KEY_ERROR = ("sort() on record rows needs a key: pass a column name "
              "(sort(key='col')) or a callable")


class _RangePartitioner:
    """row -> partition index against sampled boundaries. The columnar
    `assign_block` fast path uses np.searchsorted(side="right"), exactly
    bisect_right's semantics, so bucket membership is representation-
    independent."""

    def __init__(self, boundaries: List[Any], key, descending: bool,
                 n_parts: int):
        self.boundaries = boundaries
        self.key = key
        self.descending = descending
        self.n_parts = n_parts

    def _key_of(self, row):
        if self.key is None:
            return row
        if callable(self.key):
            return self.key(row)
        return row[self.key]

    def __call__(self, row) -> int:
        idx = bisect.bisect_right(self.boundaries, self._key_of(row))
        return self.n_parts - 1 - idx if self.descending else idx

    def assign_block(self, block) -> Optional[np.ndarray]:
        """Vectorized assignment for a dict-of-arrays block; None defers
        to the row path (callable key, object dtype, odd comparisons)."""
        if not isinstance(self.key, str) or self.key not in block:
            return None
        col = np.asarray(block[self.key])
        if col.dtype == object:
            return None
        try:
            bounds = np.asarray(self.boundaries)
            if bounds.dtype == object:
                return None
            idx = np.searchsorted(bounds, col, side="right")
        except Exception:  # noqa: BLE001 — incomparable dtypes -> row path
            return None
        if self.descending:
            idx = self.n_parts - 1 - idx
        return idx


def _sample_block_keys(block, k: int, key, seed: int, salt: int):
    """Remote sample task: at most k keys from one block, seeded without
    replacement. Returns plain Python scalars (keys only — the driver-
    resident bound is what makes the sort 'distributed' in the first
    place)."""
    from ray_tpu.data.block import BlockAccessor, _is_batch_dict

    acc = BlockAccessor(block)
    n = acc.num_rows()
    if n == 0:
        return []
    rng = np.random.default_rng(seed * 99991 + salt)
    take = min(max(k, 1), n)
    idxs = sorted(rng.choice(n, size=take, replace=False).tolist())
    if _is_batch_dict(block) and isinstance(key, str):
        col = np.asarray(block[key])
        return np.asarray(col)[idxs].tolist()
    rows = list(acc.rows())
    out = []
    for i in idxs:
        row = rows[i]
        if key is None:
            if isinstance(row, dict):
                raise ValueError(_KEY_ERROR)
            out.append(row)
        elif callable(key):
            out.append(key(row))
        else:
            out.append(row[key])
    return [v.item() if hasattr(v, "item") else v for v in out]


def _stable_desc_order(col: np.ndarray) -> np.ndarray:
    """Permutation sorting `col` descending with ties in ORIGINAL order
    (== sorted(reverse=True)): stable-ascending argsort of the reversed
    array, mapped back and reversed."""
    n = len(col)
    return (n - 1 - np.argsort(col[::-1], kind="stable"))[::-1]


def make_local_sort_transform(key, descending: bool,
                              lenient: bool = False) -> Callable:
    """Fused per-partition transform: stable local sort. `lenient`
    swallows TypeError from unorderable keys and returns the block
    as-is (groupby's best-effort ordering contract)."""

    def _row_key(row):
        if key is None:
            return row
        if callable(key):
            return key(row)
        return row[key]

    def transform(block):
        from ray_tpu.data.block import BlockAccessor, _is_batch_dict

        if _is_batch_dict(block) and isinstance(key, str) and block:
            col = np.asarray(block[key])
            if col.dtype != object:
                order = (_stable_desc_order(col) if descending
                         else np.argsort(col, kind="stable"))
                return {k: np.asarray(v)[order] for k, v in block.items()}
        rows = list(BlockAccessor(block).rows())
        try:
            rows.sort(key=_row_key, reverse=descending)
        except TypeError:
            if not lenient:
                raise
        return rows

    transform._op_name = "Sort"
    return transform


def compute_boundaries(samples: List[Any], n_parts: int) -> List[Any]:
    """n_parts-1 ascending cut points from sorted samples (equal-width
    quantiles of the sample). Fewer samples than partitions just means
    duplicate boundaries => some empty partitions, never wrong rows."""
    if not samples or n_parts <= 1:
        return []
    samples = sorted(samples)
    return [samples[(i * len(samples)) // n_parts]
            for i in range(1, n_parts)]


def sort_dataset(parent, key: Union[None, str, Callable] = None,
                 descending: bool = False, *, lenient: bool = False):
    """Range-partitioned distributed sort of `parent`; returns a lazy
    Dataset whose iteration runs sample -> keyed exchange -> local sort.
    `lenient`: unorderable keys degrade to unsorted output instead of
    raising (the groupby result-ordering contract)."""
    from ray_tpu.data.dataset import _RangeSortDataset

    return _RangeSortDataset(parent, key, descending, lenient)
