"""Per-operator execution stats for Dataset pipelines.

Equivalent of the reference's `python/ray/data/_internal/stats.py`
(`DatasetStats` + the `_StatsActor` aggregation): every fused remote
block task times its producer and each transform, then pushes one
fire-and-forget per-op timing record to a zero-CPU collector actor; after an
execution `ds.stats()` renders a per-operator wall/rows/blocks summary
for diagnosing pipeline bottlenecks.

Boundedness (the RL011-style audit of this module): the collector is a
long-lived actor fed fire-and-forget by every worker, so BOTH of its keyed
stores are bounded. The op table caps at `MAX_OP_ENTRIES` — a sender
inventing unbounded op names (or a bug tagging records per block) degrades
to a `dropped_records` counter instead of unbounded actor memory — and
transient per-window stage records (the windowed shuffle emits one entry
per window while it runs, for live visibility) are PRUNED when the stage
finishes: `fold()` collapses them into one rollup entry, so finished ops
leave nothing behind.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple


def block_rows(block: Any) -> int:
    """Best-effort row count of a block (list, dict-of-columns, ndarray,
    DataFrame)."""
    try:
        if isinstance(block, dict):
            return len(next(iter(block.values()))) if block else 0
        return len(block)
    except TypeError:
        return 1


class _StatsCollector:
    """Zero-CPU actor accumulating (op_index, op_name, wall_s, rows)
    records; one batched push per executed block. Keyed state is bounded:
    see the module docstring."""

    MAX_OP_ENTRIES = 512

    def __init__(self):
        # (index, name) -> [blocks, rows_out, wall_s]
        self._ops: Dict[Tuple[int, str], List[float]] = {}
        self._batches = 0  # record() calls == executed blocks
        self._dropped = 0  # records refused by the op-entry cap
        self._started = time.time()

    def _add(self, entries: List[Tuple[int, str, float, int]]):
        for idx, name, wall, rows in entries:
            key = (idx, name)
            agg = self._ops.get(key)
            if agg is None:
                if len(self._ops) >= self.MAX_OP_ENTRIES:
                    self._dropped += 1
                    continue
                agg = self._ops[key] = [0, 0, 0.0]
            agg[0] += 1
            agg[1] += rows
            agg[2] += wall

    def record(self, entries: List[Tuple[int, str, float, int]]):
        self._batches += 1
        self._add(entries)

    def record_stage(self, entries: List[Tuple[int, str, float, int]]):
        """Driver-side stage records (shuffle windows): aggregated like
        record() but NOT counted as an executed block — stats() flush
        barriers compare blocks_recorded against executed blocks only."""
        self._add(entries)

    def fold(self, index: int, rollup_name: str):
        """Prune finished-op records: collapse every entry at `index`
        into one `(index, rollup_name)` rollup. The per-window entries a
        running stage emitted disappear; their sums survive."""
        dead = [k for k in self._ops if k[0] == index and k[1] != rollup_name]
        if not dead:
            return
        agg = self._ops.setdefault((index, rollup_name), [0, 0, 0.0])
        for key in dead:
            b, r, w = self._ops.pop(key)
            agg[0] += b
            agg[1] += r
            agg[2] += w

    def summary(self) -> Dict[str, Any]:
        ops = [{"index": idx, "name": name, "blocks": int(b),
                "rows": int(r), "wall_s": w}
               for (idx, name), (b, r, w) in sorted(self._ops.items())]
        return {"ops": ops, "blocks_recorded": self._batches,
                "dropped_records": self._dropped,
                "elapsed_s": time.time() - self._started}


class CollectorHandle:
    """Shared ownership wrapper: datasets (and their materialized
    derivatives) hold this; when the last holder is garbage-collected a
    weakref finalizer kills the actor — per-execution collectors would
    otherwise leak one worker process per epoch."""

    def __init__(self, actor):
        self.actor = actor

    def record_stage(self, entries):
        try:
            self.actor.record_stage.remote(entries)
        except Exception:  # noqa: BLE001 — stats must never fail the stage
            pass

    def fold(self, index: int, rollup_name: str):
        try:
            self.actor.fold.remote(index, rollup_name)
        except Exception:  # noqa: BLE001
            pass


class DatasetStats:
    """Rendered summary handed back by `ds.stats()`."""

    def __init__(self, summary: Dict[str, Any],
                 backpressure: Optional[Dict[str, Any]] = None):
        self._summary = summary
        self._backpressure = backpressure

    @property
    def ops(self) -> List[Dict[str, Any]]:
        return self._summary["ops"]

    @property
    def backpressure(self) -> Optional[Dict[str, Any]]:
        """Per-op byte-budget accounting of the LAST execution (None when
        the pipeline ran without a budget): blocks admitted, bytes
        high-water mark, and seconds blocked on the budget — the op with
        the largest blocked_s is where the pipeline is bound."""
        return self._backpressure

    def __repr__(self) -> str:
        lines = ["Dataset execution stats:"]
        for op in self.ops:
            wall = op["wall_s"]
            per_block = wall / op["blocks"] if op["blocks"] else 0.0
            lines.append(
                f"  {op['name']}: {op['blocks']} blocks, "
                f"{op['rows']} rows, {wall:.3f}s wall "
                f"({per_block * 1000:.1f}ms/block)")
        if self._summary.get("dropped_records"):
            lines.append(
                f"  (dropped {self._summary['dropped_records']} records "
                "past the op-entry cap)")
        bp = self._backpressure
        if bp and bp.get("ops"):
            lines.append(
                f"  backpressure (budget {bp['total_bytes']} bytes, "
                f"bound: {bp.get('bound_op')}):")
            for op, acct in sorted(bp["ops"].items()):
                lines.append(
                    f"    {op}: {acct['blocks']} blocks, "
                    f"hwm {acct['bytes_hwm']} bytes, "
                    f"blocked {acct['blocked_s']:.3f}s")
        lines.append(f"  total elapsed: {self._summary['elapsed_s']:.3f}s")
        return "\n".join(lines)


def timed_apply(fns: List[Any], producer, args: tuple
                ) -> Tuple[Any, List[Tuple[int, str, float, int]]]:
    """Run producer + fused transforms, timing each op. Returns the
    final block and the per-op records for this block."""
    records: List[Tuple[int, str, float, int]] = []
    t0 = time.perf_counter()
    block = producer(*args) if producer is not None else args[0]
    if producer is not None:
        records.append(
            (-1, getattr(producer, "_op_name", None)
             or f"Read({getattr(producer, '__name__', 'producer')})",
             time.perf_counter() - t0, block_rows(block)))
    for i, fn in enumerate(fns):
        t1 = time.perf_counter()
        block = fn(block)
        records.append(
            (i, getattr(fn, "_op_name", None)
             or getattr(fn, "__name__", "transform"),
             time.perf_counter() - t1, block_rows(block)))
    return block, records


def make_collector() -> Optional[CollectorHandle]:
    """Spawn the zero-CPU stats actor (None if the cluster is down),
    wrapped for GC-driven reaping."""
    import weakref

    import ray_tpu

    try:
        actor = ray_tpu.remote(_StatsCollector).options(num_cpus=0).remote()
    except Exception:  # noqa: BLE001 — stats must never break execution
        return None
    handle = CollectorHandle(actor)
    weakref.finalize(handle, reap_collector, actor)
    return handle


def reap_collector(actor) -> None:
    # GC-driven finalizer: may fire on ANY thread at ANY allocation,
    # including control-plane threads (GCS/raylet RPC handlers) during
    # the window between shutdown() and a later init(). It must never
    # go through ray_tpu.kill(): _require_runtime() auto-inits when the
    # runtime is down, which from a control-plane thread deadlocks
    # against the in-progress init holding _init_lock (observed as
    # register_node stalls + missed-heartbeat node death in suite runs).
    # A dead runtime already reaped the actor; only reap on a live one.
    import ray_tpu

    runtime = ray_tpu._global_runtime
    if runtime is None:
        return
    try:
        runtime.kill_actor(actor._actor_id, no_restart=True)
    except Exception:  # noqa: BLE001 — cluster may already be down
        pass


def fetch(collector: Optional[CollectorHandle],
          expected_blocks: Optional[int] = None,
          timeout_s: float = 2.0,
          backpressure: Optional[Dict[str, Any]] = None
          ) -> Optional[DatasetStats]:
    """Summary snapshot. record() pushes are fire-and-forget from worker
    processes with no cross-client ordering vs this summary call, so
    when the caller knows how many blocks executed we poll until the
    collector has seen them all (or a short timeout)."""
    import ray_tpu

    if collector is None:
        return None
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            summary = ray_tpu.get(collector.actor.summary.remote(),
                                  timeout=10)
            if (not expected_blocks
                    or summary["blocks_recorded"] >= expected_blocks
                    or time.monotonic() >= deadline):
                return DatasetStats(summary, backpressure=backpressure)
            time.sleep(0.02)
    except Exception:  # noqa: BLE001
        return None
