"""ray_tpu.data.streaming: the sustained-ingest pipeline plane.

Turns the Dataset DAG into a many-GB dataflow engine (ROADMAP item 5,
the Data/AIR tier of PAPER.md's layer map):

- `budget`  — ByteBudget: one in-flight byte budget per pipeline
  execution, negotiated against object-store capacity, with per-op
  backpressure accounting (`stats()` says where the pipeline is bound).
- `shuffle` — windowed push shuffle: all-to-all whose working set may
  exceed memory degrades into windows that spill through the store's
  disk tier instead of OOMing.
- `lineage` — per-block recipes + recomputed-block accounting: a node
  death mid-pipeline recomputes only the lost partitions (core task
  specs first, data-tier replay as fallback), never a restart.
- `ingest`  — ShardIterator: per-host double-buffered prefetch feeding
  `train.session` with step-stall accounting.

See docs/DATA_STREAMING.md for the window/budget model and contracts.
"""

from ray_tpu.data.streaming.budget import (ByteBudget, current_budget,
                                           pipeline_budget)
from ray_tpu.data.streaming.ingest import ShardIterator, iter_shards
from ray_tpu.data.streaming.lineage import BlockLineage, core_reconstructions
from ray_tpu.data.streaming.shuffle import iter_shuffled_refs

__all__ = [
    "ByteBudget", "BlockLineage", "ShardIterator", "core_reconstructions",
    "current_budget", "iter_shards", "iter_shuffled_refs",
    "pipeline_budget",
]
