"""ByteBudget: the global in-flight byte budget of a pipeline execution.

The seed-era executor throttled on block COUNTS (`max_buffered_blocks_per_op`),
which says nothing about memory: 16 buffered 128 MiB shuffle buckets and 16
buffered 4 KiB filter outputs were the same "16". The streaming ingest plane
replaces that with one byte budget shared by every operator of an execution
(reference: Ray Data's `StreamingExecutor` resource budgets,
`streaming_executor_state.py` `_execution_allowed`): operators `acquire()`
an estimated output size before submitting a task and the pump stalls when
the pipeline's total in-flight bytes would exceed the budget — so a shuffle
whose working set exceeds memory degrades into windows whose SEALED outputs
spill through the object store's disk tier, while the *unsealed* (in-flight)
set stays bounded and the node never OOMs.

Accounting is per-op: `stats()` reports, for each operator, bytes in flight
(high-water mark), blocks admitted, and seconds spent blocked on the budget
— the op with the largest blocked time is where the pipeline is bound.

The budget is negotiated against the local object store at execution start
(`negotiated()`): explicit knob first (`DataContext.inflight_budget_bytes` /
`RAY_TPU_DATA_INFLIGHT_BUDGET_BYTES`), else 25% of store capacity with a
64 MiB floor. One execution = one budget; nested stages (a shuffle driving
its parent pipeline) share the outermost budget via `pipeline_budget()`.

**Tenants.** On a multi-job node (jobs-as-tenants, PR 17) every pipeline
execution ALSO charges a process-global per-tenant ledger, keyed by the
submitting job (`DataContext.resolved_tenant()`: explicit `tenant` field,
else RAY_TPU_JOB_ID, else "default"). `data_tenant_budget_bytes` caps any
one tenant's in-flight bytes ACROSS its concurrent executions: admission
over the cap is refused — reject-with-backpressure, counted in
`tenant_stats()["rejections"]` — rather than letting one job's wide
shuffle silently starve every other job's pipeline out of the shared
store. Same progress guarantee as the budget itself: a tenant with
nothing in flight is always admitted, so a cap smaller than one block
degrades to block-at-a-time execution, never deadlock. Cross-budget
releases are observed by acquire()'s 1-second poll (budgets don't share
a condition variable — the poll bounds the staleness instead).
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from typing import Any, Dict, Iterator, Optional

_BUDGET_FLOOR = 64 * 1024 * 1024
_CAPACITY_FRACTION = 0.25

_op_seq = itertools.count(1)


def unique_op(name: str) -> str:
    """A ledger key that is unique per stage INSTANCE. Two executions can
    share one budget (nested stages on purpose; interleaved same-thread
    iterations through the thread-local by accident) — with instance
    keys, one execution's release_op can never zero a sibling's charges,
    and backpressure stats stay attributable."""
    return f"{name}#{next(_op_seq)}"


def _local_store_capacity() -> Optional[int]:
    """Capacity of this node's object store, best-effort: the in-process
    head node's store directly, else one debug_state RPC to the raylet."""
    import ray_tpu

    node = getattr(ray_tpu, "_global_node", None)
    if node is not None:
        try:
            return int(node.raylet.store.capacity)
        except Exception:  # noqa: BLE001 — node mid-shutdown
            pass
    runtime = getattr(ray_tpu, "_global_runtime", None)
    if runtime is None:
        return None
    try:
        return int(runtime.raylet.call("debug_state", timeout=5)
                   ["store"]["capacity_bytes"])
    except Exception:  # noqa: BLE001 — no cluster / raylet unreachable
        return None


class _OpAccount:
    __slots__ = ("blocks", "bytes_in_flight", "bytes_hwm", "blocked_s",
                 "bytes_total")

    def __init__(self):
        self.blocks = 0
        self.bytes_in_flight = 0
        self.bytes_hwm = 0
        self.blocked_s = 0.0
        self.bytes_total = 0


class _TenantLedger:
    """Process-global per-tenant in-flight byte accounting, mirrored from
    every ByteBudget's ledger mutations. Own lock, always acquired AFTER
    a budget's condition lock (one-way ordering — no deadlock)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: Dict[str, Dict[str, int]] = {}

    def _slot(self, tenant: str) -> Dict[str, int]:
        slot = self._tenants.get(tenant)
        if slot is None:
            slot = self._tenants[tenant] = {
                "bytes_in_flight": 0, "bytes_hwm": 0, "bytes_total": 0,
                "rejections": 0}
        return slot

    @staticmethod
    def limit() -> int:
        from ray_tpu.core.config import GLOBAL_CONFIG

        return GLOBAL_CONFIG.data_tenant_budget_bytes

    def would_exceed(self, tenant: str, nbytes: int) -> bool:
        """Over the per-tenant cap? False when uncapped or when the
        tenant has nothing in flight (the tenant-level progress
        guarantee: an idle tenant always gets its first block)."""
        lim = self.limit()
        if lim <= 0:
            return False
        with self._lock:
            slot = self._slot(tenant)
            return (slot["bytes_in_flight"] > 0
                    and slot["bytes_in_flight"] + nbytes > lim)

    def add(self, tenant: str, delta: int) -> None:
        with self._lock:
            slot = self._slot(tenant)
            slot["bytes_in_flight"] = max(0, slot["bytes_in_flight"] + delta)
            if delta > 0:
                slot["bytes_total"] += delta
            slot["bytes_hwm"] = max(slot["bytes_hwm"],
                                    slot["bytes_in_flight"])

    def note_rejection(self, tenant: str) -> None:
        with self._lock:
            self._slot(tenant)["rejections"] += 1

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {t: dict(s) for t, s in self._tenants.items()}

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()


_TENANTS = _TenantLedger()


def tenant_stats() -> Dict[str, Dict[str, int]]:
    """Per-tenant in-flight/hwm/total bytes and budget rejections across
    every execution this process has run."""
    return _TENANTS.stats()


def reset_tenant_stats() -> None:
    _TENANTS.reset()


class ByteBudget:
    """Shared in-flight byte ledger with per-op backpressure accounting.

    Progress guarantee: an op with nothing in flight is always admitted,
    even when its single block exceeds the whole budget — otherwise a
    block larger than the budget would deadlock the pipeline instead of
    degrading it to window-at-a-time execution.

    Every ledger mutation mirrors into the process-global per-tenant
    ledger under the tenant resolved at construction, so concurrent
    executions of one job are capped TOGETHER by
    `data_tenant_budget_bytes` (see module docstring).
    """

    def __init__(self, total_bytes: int):
        self.total = int(total_bytes)
        self._used = 0
        self._cond = threading.Condition()
        self._ops: Dict[str, _OpAccount] = {}
        from ray_tpu.data.context import DataContext

        self.tenant = DataContext.get_current().resolved_tenant()

    @classmethod
    def negotiated(cls) -> "ByteBudget":
        from ray_tpu.data.context import DataContext

        configured = DataContext.get_current().resolved_inflight_budget_bytes()
        if configured > 0:
            return cls(configured)
        capacity = _local_store_capacity()
        if capacity is None:
            return cls(_BUDGET_FLOOR)
        return cls(max(_BUDGET_FLOOR, int(capacity * _CAPACITY_FRACTION)))

    # ------------------------------------------------------------- ledger

    def _account(self, op: str) -> _OpAccount:
        acct = self._ops.get(op)
        if acct is None:
            acct = self._ops[op] = _OpAccount()
        return acct

    def acquire(self, op: str, nbytes: int, timeout: Optional[float] = None
                ) -> bool:
        """Charge `nbytes` against the budget for `op`, blocking while the
        pipeline is over budget (unless this op has nothing in flight —
        the progress guarantee). Returns False only on timeout."""
        nbytes = max(0, int(nbytes))
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            acct = self._account(op)
            t0 = None
            while True:
                over_budget = (self._used + nbytes > self.total
                               and acct.bytes_in_flight > 0)
                # The tenant cap is checked INSIDE the wait loop: another
                # budget of the same tenant releasing bytes unblocks this
                # acquire at the next 1 s poll (no shared condition).
                over_tenant = _TENANTS.would_exceed(self.tenant, nbytes)
                if not (over_budget or over_tenant):
                    break
                if t0 is None:
                    t0 = time.monotonic()
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    acct.blocked_s += time.monotonic() - t0
                    if over_tenant:
                        # Reject-with-backpressure, and make the denial
                        # visible — never silent starvation.
                        _TENANTS.note_rejection(self.tenant)
                    return False
                self._cond.wait(min(1.0, remaining)
                                if remaining is not None else 1.0)
            if t0 is not None:
                acct.blocked_s += time.monotonic() - t0
            self._used += nbytes
            acct.blocks += 1
            acct.bytes_in_flight += nbytes
            acct.bytes_total += nbytes
            acct.bytes_hwm = max(acct.bytes_hwm, acct.bytes_in_flight)
            _TENANTS.add(self.tenant, nbytes)
            return True

    def try_acquire(self, op: str, nbytes: int) -> bool:
        """Non-blocking acquire. Single-threaded pumps MUST use this (a
        blocking acquire would deadlock: the pump's own yield path is the
        only thing that releases charges) and drain their in-flight work
        on refusal, crediting the wait via `note_blocked`."""
        return self.acquire(op, nbytes, timeout=0)

    def note_blocked(self, op: str, seconds: float):
        """Credit budget-blocked time observed OUTSIDE acquire() (the
        try_acquire/drain pattern) to the op's backpressure account."""
        with self._cond:
            self._account(op).blocked_s += max(0.0, seconds)

    def adjust(self, op: str, delta: int):
        """Re-charge an in-flight block once its ACTUAL size is known
        (acquire charged the op's estimate). Never blocks: the bytes
        already exist; the correction only makes future admission honest."""
        with self._cond:
            acct = self._account(op)
            delta = max(delta, -acct.bytes_in_flight)
            self._used += delta
            acct.bytes_in_flight += delta
            acct.bytes_total += max(0, delta)
            acct.bytes_hwm = max(acct.bytes_hwm, acct.bytes_in_flight)
            _TENANTS.add(self.tenant, delta)
            if delta < 0:
                self._cond.notify_all()

    def release(self, op: str, nbytes: int):
        with self._cond:
            acct = self._account(op)
            nbytes = min(max(0, int(nbytes)), acct.bytes_in_flight)
            self._used = max(0, self._used - nbytes)
            acct.bytes_in_flight -= nbytes
            _TENANTS.add(self.tenant, -nbytes)
            self._cond.notify_all()

    def release_op(self, op: str):
        """Drop everything an op still has charged (execution finished or
        aborted). The account itself is retained for `stats()` — the key
        space is the stage names of ONE execution (bounded by the plan)
        and the budget dies with its execution; `reset()` is the drain
        for callers that reuse a budget across executions."""
        with self._cond:
            acct = self._ops.get(op)
            if acct is not None and acct.bytes_in_flight:
                self._used = max(0, self._used - acct.bytes_in_flight)
                _TENANTS.add(self.tenant, -acct.bytes_in_flight)
                acct.bytes_in_flight = 0
            self._cond.notify_all()

    def reset(self):
        """Forget every charge and account (reusing a budget across
        executions starts from a clean ledger)."""
        with self._cond:
            self._ops.clear()
            _TENANTS.add(self.tenant, -self._used)
            self._used = 0
            self._cond.notify_all()

    # -------------------------------------------------------------- stats

    @property
    def used(self) -> int:
        with self._cond:
            return self._used

    def stats(self) -> Dict[str, Any]:
        """Per-op backpressure: where the pipeline is bound."""
        with self._cond:
            ops = {
                op: {"blocks": a.blocks, "bytes_total": a.bytes_total,
                     "bytes_in_flight": a.bytes_in_flight,
                     "bytes_hwm": a.bytes_hwm,
                     "blocked_s": round(a.blocked_s, 4)}
                for op, a in self._ops.items()
            }
            bound = max(ops, key=lambda o: ops[o]["blocked_s"]) \
                if ops else None
        return {"total_bytes": self.total, "used_bytes": self._used,
                "tenant": self.tenant, "ops": ops, "bound_op": bound}


# --- execution-scoped budget sharing ----------------------------------------
#
# A pipeline execution is a driver-side call tree: the fused-transform
# executor of a shuffle's OUTPUT iterates the shuffle, which iterates the
# parent dataset's executor. One budget must govern the whole tree (a
# per-stage budget would multiply the cap by pipeline depth), so the
# outermost stage installs the budget here and inner stages adopt it.

_tls = threading.local()


def current_budget() -> Optional[ByteBudget]:
    return getattr(_tls, "budget", None)


@contextlib.contextmanager
def pipeline_budget(budget: Optional[ByteBudget] = None
                    ) -> Iterator[ByteBudget]:
    """Adopt the execution's budget, or install `budget` (negotiating a
    fresh one when None) as the tree's budget if this is the outermost
    stage."""
    existing = current_budget()
    if existing is not None:
        yield existing
        return
    owned = budget if budget is not None else ByteBudget.negotiated()
    _tls.budget = owned
    try:
        yield owned
    finally:
        _tls.budget = None
