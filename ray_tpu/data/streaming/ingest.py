"""Train ingest: per-host double-buffered prefetch with step-stall accounting.

A training step that waits on its next batch burns accelerator time; the
contract of this module is that input never stalls the step. A
`ShardIterator` wraps one consumer's view of a streaming execution (a
`StreamSplitDataIterator` from `streaming_split`, or a whole-dataset
iterator) and:

- runs a PREFETCH thread that pulls the next `data_prefetch_shards` blocks
  (default 2 — double buffering) into a bounded queue ahead of the
  consumer. The pull is `ray_tpu.get` on this host, so the block rides the
  transfer plane's location-aware pipelined pull straight into the local
  store BEFORE the step needs it (locality routing is the transfer
  plane's: pulls stripe across every node holding a copy);
- accounts every batch handed out: `stall_ms` (time the consumer waited on
  the queue — input-bound time) vs `step_ms` (time between batch requests
  — compute time), so `ingest_stats()` answers "is input stalling the
  step" with numbers (`stall_frac` < 0.10 is the bench gate);
- re-windows on re-iteration: a second epoch re-drives the shared
  streaming execution (the split coordinator bumps its epoch and the
  windowed shuffle re-runs) instead of re-materializing the dataset.

Picklable: prefetch state is created lazily on first iteration, so a
ShardIterator ships to a train worker and runs its prefetch thread there
(per-host buffering, not driver-side).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.iterator import batch_blocks

_END = object()


class _IngestClock:
    """Stall/step accounting for one consumer.

    The FIRST batch of an epoch is accounted separately
    (`first_batch_ms`): nothing can overlap a pipeline's cold start, so
    folding it into stall would misattribute fill latency as per-step
    input starvation. `stall_frac` is the steady-state number — the one
    the "input never stalls the step" contract gates on."""

    def __init__(self):
        self.steps = 0
        self.stall_ms_total = 0.0
        self.step_ms_total = 0.0
        self.first_batch_ms = 0.0
        self._epoch_first = True
        self._last_yield: Optional[float] = None

    def epoch(self):
        self._epoch_first = True
        self._last_yield = None

    def request(self) -> float:
        now = time.perf_counter()
        if self._last_yield is not None:
            self.step_ms_total += (now - self._last_yield) * 1000.0
        return now

    def delivered(self, t_request: float):
        now = time.perf_counter()
        waited = (now - t_request) * 1000.0
        if self._epoch_first:
            self.first_batch_ms += waited
            self._epoch_first = False
        else:
            self.stall_ms_total += waited
        self.steps += 1
        self._last_yield = now

    def stats(self) -> Dict[str, Any]:
        busy = self.stall_ms_total + self.step_ms_total
        steady = max(0, self.steps - 1)
        return {
            "steps": self.steps,
            "stall_ms_total": round(self.stall_ms_total, 3),
            "step_ms_total": round(self.step_ms_total, 3),
            "first_batch_ms": round(self.first_batch_ms, 3),
            "stall_ms_per_step": round(
                self.stall_ms_total / steady, 3) if steady else 0.0,
            "stall_frac": round(self.stall_ms_total / busy, 4) if busy
            else 0.0,
        }


class ShardIterator:
    """Prefetching, stall-accounting view of a stream of blocks."""

    def __init__(self, source: Any, prefetch: Optional[int] = None):
        self._source = source
        self._prefetch = prefetch
        self._clock = _IngestClock()
        self._epochs = 0

    # ------------------------------------------------------------- plumbing

    def _resolved_prefetch(self) -> int:
        if self._prefetch is not None:
            return self._prefetch
        from ray_tpu.data.context import DataContext

        return DataContext.get_current().resolved_prefetch_shards()

    def _source_blocks(self) -> Iterator[Any]:
        src = self._source
        if hasattr(src, "_iter_blocks"):        # StreamSplitDataIterator
            return src._iter_blocks()
        if hasattr(src, "_iter_block_values"):  # Dataset
            return src._iter_block_values()
        return iter(src)

    def _pumped(self, make_iter) -> Iterator[Any]:
        """Items from `make_iter()`, produced ahead by the prefetch
        thread. The bounded queue IS the double buffer (budget: the
        producer parks on put() when the consumer falls behind; depth =
        prefetch knob) and drains to termination on both normal
        exhaustion and generator close. Everything upstream of the queue
        — the coordinator round trip, the transfer-plane pull AND the
        block->batch conversion — overlaps with the consumer's step."""
        depth = self._resolved_prefetch()
        if depth <= 0:
            yield from make_iter()
            return
        buf: "queue.Queue" = queue.Queue(maxsize=depth)
        stop = threading.Event()

        def _put(item) -> bool:
            """Stop-aware bounded put — EVERY producer write, including
            the terminal sentinel and the error relay, must yield to an
            abandoned consumer's stop() or the thread (and its pinned
            blocks) leaks past the join."""
            while not stop.is_set():
                try:
                    buf.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def _pump():
            try:
                for item in make_iter():
                    if not _put(item):
                        return
                _put(_END)
            except BaseException as e:  # noqa: BLE001 — surface to consumer
                _put(e)

        thread = threading.Thread(target=_pump, name="ingest-prefetch",
                                  daemon=True)
        thread.start()
        try:
            while True:
                item = buf.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            thread.join(timeout=5.0)

    def _iter_blocks(self) -> Iterator[Any]:
        self._epochs += 1
        yield from self._pumped(self._source_blocks)

    # ------------------------------------------------------------ consumers

    def iter_batches(self, *, batch_size: int = 256,
                     drop_last: bool = False,
                     prefetch_batches: Optional[int] = None
                     ) -> Iterator[Dict[str, Any]]:
        if prefetch_batches is not None:
            self._prefetch = prefetch_batches
        self._epochs += 1
        clock = self._clock
        clock.epoch()
        batches = self._pumped(
            lambda: batch_blocks(self._source_blocks(), batch_size,
                                 drop_last))
        while True:
            t_req = clock.request()
            try:
                batch = next(batches)
            except StopIteration:
                return
            clock.delivered(t_req)
            yield batch

    def iter_rows(self) -> Iterator[Any]:
        for block in self._iter_blocks():
            yield from BlockAccessor(block).rows()

    # ----------------------------------------------------------- accounting

    def ingest_stats(self) -> Dict[str, Any]:
        out = self._clock.stats()
        out["epochs"] = self._epochs
        out["prefetch_depth"] = self._resolved_prefetch()
        # Locality routing outcome of the underlying split (coordinator
        # handed blocks already resident on this node vs remote pulls);
        # absent for sources that don't track it.
        src = self._source
        if hasattr(src, "locality_stats"):
            out.update(src.locality_stats())
        return out

    def __reduce__(self):
        return (ShardIterator, (self._source, self._prefetch))


def iter_shards(dataset, n: int, *, prefetch: Optional[int] = None,
                equal: bool = False) -> List[ShardIterator]:
    """n coordinated prefetching shards over ONE shared streaming
    execution — the train ingest entry point (`DataIterator.iter_shards`)."""
    splits = dataset.streaming_split(n, equal=equal)
    return [ShardIterator(s, prefetch) for s in splits]
