"""Block lineage: bounded recomputation of lost pipeline blocks.

Every block a streaming execution emits records its recipe — (producer,
args, fused transforms) — so a node death mid-pipeline recomputes only the
lost partitions, never the whole pipeline (Exoshuffle's case: shuffle as
lineage-recoverable application code on the task runtime, not a bespoke
service). Recovery is two-tier:

- The CORE tier recovers transparently: the owner retains every submitted
  task spec, and a `get` on a lost object re-executes the creating task
  bottom-up (`core/runtime.py _try_reconstruct`, bounded by
  `max_object_reconstructions` / `max_reconstruction_depth`). The runtime
  counts these in `reconstructions_total`.
- The DATA tier here is the fallback for blocks the core cannot replay
  (e.g. the creating task exhausted its reconstruction budget, or the
  block was driver-materialized): `resolve()` re-runs the recorded fused
  task as a fresh submission, bounded per block.

Both tiers feed `accounting()`, the recomputed-block evidence the chaos
plane asserts on: after a node kill mid-shuffle, recomputed blocks must be
≤ the dead node's resident partition count — bounded re-execution, never a
restart and never a hang.

Records are dropped as soon as a block is consumed (`forget`) and the
registry is cleared when the execution ends — keyed state drains with the
pipeline (the RL013 discipline this module exists to enforce elsewhere).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


def core_reconstructions() -> int:
    """The runtime's lifetime count of core-tier lineage re-executions."""
    import ray_tpu

    runtime = getattr(ray_tpu, "_global_runtime", None)
    return getattr(runtime, "reconstructions_total", 0) if runtime else 0


class _BlockRecord:
    __slots__ = ("producer", "args", "transforms", "attempts")

    def __init__(self, producer: Optional[Callable], args: tuple,
                 transforms: List[Callable]):
        self.producer = producer
        self.args = args
        self.transforms = transforms
        self.attempts = 0


class BlockLineage:
    """Driver-side registry: block ref -> recipe, with bounded recompute.

    The registry is a bounded FIFO (`MAX_RECORDS`): recipes whose args
    hold ObjectRefs PIN those upstream objects, and a consumer that takes
    refs without resolving them (materialize, the split coordinator)
    would otherwise pin a whole epoch of shuffle buckets. Eviction drops
    the OLDEST recipe — the consumption frontier stays covered, and
    blocks past it still have the core tier's retained task specs."""

    MAX_RECORDS = 128

    def __init__(self, max_recomputes_per_block: Optional[int] = None):
        from collections import OrderedDict

        from ray_tpu.core.config import GLOBAL_CONFIG

        self._records: "OrderedDict[bytes, _BlockRecord]" = OrderedDict()
        self._max_attempts = (max_recomputes_per_block
                              if max_recomputes_per_block is not None
                              else GLOBAL_CONFIG.max_object_reconstructions)
        self.recomputed_blocks = 0
        self._core_base = core_reconstructions()

    def __len__(self) -> int:
        return len(self._records)

    def record(self, ref: Any, producer: Optional[Callable], args: tuple,
               transforms: List[Callable]):
        self._records[ref.object_id.binary()] = _BlockRecord(
            producer, tuple(args), list(transforms))
        while len(self._records) > self.MAX_RECORDS:
            self._records.popitem(last=False)

    def forget(self, ref: Any):
        self._records.pop(ref.object_id.binary(), None)

    def clear(self):
        self._records.clear()

    # ----------------------------------------------------------- recovery

    def _heal_arg(self, arg: Any) -> Any:
        """Make one recipe argument fetchable again. A driver-side get on
        a lost ref is what triggers the CORE tier (the driver owns every
        pipeline task, so `_try_reconstruct` re-runs the creating task);
        if even that fails and the arg has its own recipe, recurse into
        the data tier. Loss-shaped errors only — a user exception inside
        a dependency propagates untouched."""
        from ray_tpu.object_ref import ObjectRef

        if not isinstance(arg, ObjectRef):
            return arg
        import ray_tpu
        from ray_tpu.exceptions import ObjectLostError, RaySystemError

        try:
            # Value discarded: the point is re-sealing the object so the
            # resubmitted task's worker can fetch it.
            ray_tpu.get(arg)
            return arg
        except (ObjectLostError, RaySystemError):
            if arg.object_id.binary() in self._records:
                return self.recompute(arg)
            # No data-tier recipe (e.g. one bucket of a multi-return map
            # task), but the driver OWNS the creating task: have the core
            # re-execute it — this also covers tasks that "completed"
            # with a loss-shaped error because their own dependency died
            # (the core recursively rebuilds dead deps, bottom-up).
            runtime = getattr(ray_tpu, "_global_runtime", None)
            if runtime is None or not runtime.reexecute_task_for(
                    arg.object_id):
                raise
            ray_tpu.get(arg)  # wait out the re-execution (may re-raise)
            return arg

    def recompute(self, ref: Any) -> Any:
        """Re-submit the recorded fused task for a lost block; returns the
        NEW ref. Ref-valued args are healed first (core reconstruction,
        then recursive data-tier recompute), so a reduce whose bucket
        died re-runs only the lost maps, bottom-up. Raises KeyError when
        the block has no record and ObjectLostError once the per-block
        attempt budget is spent."""
        import ray_tpu
        from ray_tpu.data.executor import _fused_apply
        from ray_tpu.exceptions import ObjectLostError

        rec = self._records[ref.object_id.binary()]
        if rec.attempts >= self._max_attempts:
            raise ObjectLostError(ref.object_id)
        rec.attempts += 1
        self.recomputed_blocks += 1
        logger.warning("block %s lost beyond core recovery: re-running its "
                       "fused task (data-tier attempt %d)",
                       ref.object_id.hex()[:12], rec.attempts)
        args = tuple(self._heal_arg(a) for a in rec.args)
        rec.args = args
        new_ref = ray_tpu.remote(_fused_apply).remote(
            rec.transforms, rec.producer, *args)
        # The recipe now describes the new ref; retire the old key.
        self._records[new_ref.object_id.binary()] = rec
        self._records.pop(ref.object_id.binary(), None)
        return new_ref

    def resolve(self, ref: Any, timeout: Optional[float] = None) -> Any:
        """`ray_tpu.get` with the data-tier fallback: the core recovers
        what it can transparently inside get(); anything still lost after
        that — including a task that "completed" with a loss-shaped error
        because its dependency died under it — re-runs from the recorded
        recipe, bounded per block. Successful delivery retires the
        recipe (and with it the pins on upstream refs)."""
        import ray_tpu
        from ray_tpu.exceptions import ObjectLostError, RaySystemError

        while True:
            try:
                # RayTaskError(ObjectLostError) raises as an instance of
                # its cause (as_instanceof_cause), so one except arm sees
                # both direct loss and loss that poisoned a dependent
                # task's result.
                value = ray_tpu.get(ref, timeout=timeout)
            except (ObjectLostError, RaySystemError):
                if ref.object_id.binary() not in self._records:
                    raise
                ref = self.recompute(ref)
                continue
            self.forget(ref)
            return value

    # --------------------------------------------------------- accounting

    def accounting(self) -> Dict[str, int]:
        """Recomputed-block evidence for bounded-recovery asserts."""
        return {
            "dataplane_recomputed_blocks": self.recomputed_blocks,
            "core_reconstructions": core_reconstructions() - self._core_base,
        }
