"""Windowed push shuffle: all-to-all under a byte budget.

The seed-era `Dataset._push_shuffle` materialized every parent block, then
submitted ALL map tasks and ALL reduce tasks at once — fine for toy data,
an OOM for a working set past memory, and a whole-pipeline restart if any
of it died. This module re-runs the same two-stage exchange (reference
`push_based_shuffle.py`) as a *windowed* streaming plan:

- **Map windows.** Parent blocks stream in (never materialized as a list)
  and are grouped into windows whose estimated bytes fit a slice of the
  pipeline's ByteBudget. A window's scatter tasks run with budget-charged
  admission and the next window starts only once the previous window's
  outputs are SEALED — sealed buckets are spillable, so a shuffle whose
  working set exceeds memory degrades into windows that flow through the
  object store's disk tier instead of OOMing. Unsealed (in-flight) bytes
  stay bounded by the budget at all times.
- **Reduce.** After the map barrier (inherent to all-to-all), each output
  partition's buckets concat-reduce with bounded in-flight admission;
  partitions yield in order and their bucket refs drop as soon as the
  reduce lands (eager free of intermediates).
- **Recovery.** Every map/reduce task spec is retained by the owner, so a
  node death mid-shuffle recomputes only the lost partitions through the
  core lineage tier (`runtime._try_reconstruct`) — bounded by the dead
  node's resident block count, never a restart. `BlockLineage.accounting`
  reads the recompute evidence.

Row-level output is IDENTICAL to the unwindowed exchange for a given
(mode, seed): scatter draws are salted by each block's global index and
reduces by partition index, so windowing is invisible to determinism.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Iterator, List, Optional

logger = logging.getLogger(__name__)


def _block_size(ref: Any) -> Optional[int]:
    """Best-effort sealed size of a completed block: the owner's own
    completion record first (free — the worker pushed it with the
    result), the object directory as fallback. Never on the per-block
    hot path unless the local record is missing."""
    import ray_tpu

    runtime = getattr(ray_tpu, "_global_runtime", None)
    if runtime is None:
        return None
    size = runtime.local_result_size(ref.object_id)
    if size:
        return size
    try:
        entry = runtime.gcs.call("object_locations_get",
                                 {"object_id": ref.object_id}, timeout=5)
    except Exception:  # noqa: BLE001 — size is an estimate, never fatal
        return None
    if not entry.get("known"):
        return None
    return int(entry["size"]) or None


def iter_shuffled_refs(parent_refs: Iterator[Any], n_out: int, *,
                       mode: str, seed: Optional[int],
                       key_fn: Optional[Callable],
                       budget, stage_stats=None,
                       stats: Optional[Dict[str, Any]] = None,
                       resources: Optional[Dict[str, Any]] = None,
                       lineage=None) -> Iterator[Any]:
    """Run the windowed two-stage exchange; yields reduce-output refs in
    partition order. `stats` (optional dict) is filled with window/bytes
    accounting; `stage_stats` (optional CollectorHandle) receives
    per-window stage records, folded into one rollup per stage at the
    end (finished-window records are pruned, not retained). `lineage`
    (optional BlockLineage) records each reduce partition's recipe —
    bucket refs included — so a partition whose node dies recomputes
    bottom-up instead of failing the epoch."""
    import ray_tpu
    from ray_tpu.data.context import DataContext
    from ray_tpu.data.dataset import (_shuffle_map_block,
                                      _shuffle_reduce_blocks)

    from ray_tpu.data.streaming.budget import unique_op

    ctx = DataContext.get_current()
    op_map = unique_op("ShuffleMap")
    op_red = unique_op("ShuffleReduce")
    max_in_flight = ctx.max_tasks_in_flight_per_op
    est_default = ctx.target_min_block_size
    window_bytes = max(budget.total // 4, 1)
    smap = ray_tpu.remote(_shuffle_map_block)
    sred = ray_tpu.remote(_shuffle_reduce_blocks)
    if resources:
        # Stage tasks honor Dataset.with_resources like fused tasks do.
        smap = smap.options(**resources)
        sred = sred.options(**resources)

    import time as _time

    buckets: List[List[Any]] = [[] for _ in range(n_out)]
    in_flight: Dict[Any, int] = {}   # sentinel ref -> charged bytes
    windows = 0
    cur_bytes = 0
    cur_blocks = 0
    total_bytes = 0
    total_blocks = 0
    win_t0 = _time.perf_counter()

    def _complete(refs):
        for r in refs:
            budget.release(op_map, in_flight.pop(r))

    def _drain(to: int):
        while len(in_flight) > to:
            ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                    timeout=30.0)
            _complete(ready)

    def _close_window():
        nonlocal windows, cur_bytes, cur_blocks, win_t0
        _drain(0)  # window barrier: outputs sealed => spillable
        if stage_stats is not None:
            stage_stats.record_stage(
                [(-2, f"ShuffleMap[window {windows}]",
                  _time.perf_counter() - win_t0, cur_blocks)])
        windows += 1
        cur_bytes = 0
        cur_blocks = 0
        win_t0 = _time.perf_counter()

    def _admit(op: str, size: int, drain) -> None:
        """try_acquire + drain-on-refusal (a blocking acquire would
        deadlock the single-threaded stage driver — its own drain is what
        releases charges). The budget's progress guarantee admits once
        the op has nothing charged, so this terminates."""
        t0 = None
        while not budget.try_acquire(op, size):
            if t0 is None:
                t0 = _time.perf_counter()
            drain()
        if t0 is not None:
            budget.note_blocked(op, _time.perf_counter() - t0)

    try:
        for salt, ref in enumerate(parent_refs):
            size = _block_size(ref) or est_default
            if cur_blocks and cur_bytes + size > window_bytes:
                _close_window()
            _admit(op_map, size,
                   lambda: _drain(max(0, len(in_flight) - 1)))
            out = smap.options(num_returns=n_out).remote(
                ref, n_out, mode, seed, salt, key_fn)
            outs = [out] if n_out == 1 else list(out)
            for j, b in enumerate(outs):
                buckets[j].append(b)
            in_flight[outs[0]] = size
            cur_bytes += size
            cur_blocks += 1
            total_bytes += size
            total_blocks += 1
            _drain(max_in_flight - 1)
        if cur_blocks:
            _close_window()
    finally:
        # Error paths must not leave charges behind (the budget may be
        # shared by sibling stages of the same execution).
        _drain(0)
        budget.release_op(op_map)

    if stats is not None:
        stats.update({"windows": windows, "input_blocks": total_blocks,
                      "input_bytes": total_bytes,
                      "window_bytes": window_bytes})
    if stage_stats is not None:
        stage_stats.fold(-2, "ShuffleMap")

    # ---- reduce: bounded in-flight, yield in partition order -------------
    # Locality routing: each partition's reduce concats buckets already
    # scattered across the cluster — pin it (softly) to the node holding
    # the most bucket bytes so the concat reads shared memory instead of
    # dragging buckets over the wire. Advisory: any directory miss falls
    # back to default placement (query/locality.py).
    from ray_tpu.data.query import locality
    route_reduces = ctx.resolved_locality_routing()
    est_part = max(total_bytes // max(1, n_out), 1)
    reduce_in_flight: Dict[Any, int] = {}  # ref -> partition index
    ready_parts: Dict[int, Any] = {}
    emit = 0
    red_t0 = _time.perf_counter()

    def _reap(block: bool):
        while reduce_in_flight:
            ready, _ = ray_tpu.wait(list(reduce_in_flight), num_returns=1,
                                    timeout=30.0 if block else 0.0)
            for r in ready:
                j = reduce_in_flight.pop(r)
                ready_parts[j] = r
                buckets[j] = []  # intermediates freed as soon as consumed
            if ready or not block:
                return

    next_submit = 0
    t_blocked = None
    try:
        while emit < n_out:
            # Yield ready partitions in order FIRST: the yield is what
            # releases their charges, so it must never sit behind a
            # refused admission.
            if emit in ready_parts:
                yield ready_parts.pop(emit)
                budget.release(op_red, est_part)
                emit += 1
                continue
            if (next_submit < n_out
                    and len(reduce_in_flight) < max_in_flight
                    and budget.try_acquire(op_red, est_part)):
                if t_blocked is not None:
                    budget.note_blocked(
                        op_red, _time.perf_counter() - t_blocked)
                    t_blocked = None
                part_buckets = buckets[next_submit]
                sred_part = sred
                if route_reduces and part_buckets:
                    opts = locality.reduce_affinity(part_buckets)
                    if opts is not None:
                        # .options() merges, so resources survive the pin.
                        sred_part = sred.options(**opts)
                red_ref = sred_part.remote(mode, seed, next_submit,
                                           *part_buckets)
                if lineage is not None:
                    lineage.record(
                        red_ref, _shuffle_reduce_blocks,
                        (mode, seed, next_submit, *part_buckets), [])
                reduce_in_flight[red_ref] = next_submit
                next_submit += 1
                continue
            if next_submit < n_out and t_blocked is None \
                    and len(reduce_in_flight) < max_in_flight:
                t_blocked = _time.perf_counter()  # refusal was the budget's
            _reap(block=True)
    finally:
        budget.release_op(op_red)
        if stage_stats is not None:
            stage_stats.record_stage(
                [(-3, "ShuffleReduce", _time.perf_counter() - red_t0,
                  n_out)])
