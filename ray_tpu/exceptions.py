"""User-facing exception types.

Parity with the reference's `python/ray/exceptions.py`: task errors wrap the
remote traceback, actor errors and death causes, object loss/owner-death errors.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all framework errors."""


class RayTaskError(RayTpuError):
    """A task raised an exception; re-raised on `get` with the remote traceback."""

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"Task {function_name} failed:\n{traceback_str}")

    def __reduce__(self):
        # Always pickle as the base class; the dynamic dual-inheritance class
        # from as_instanceof_cause() is rebuilt on the receiving side.
        return (_rebuild_task_error, (self.function_name, self.traceback_str, self.cause))

    def as_instanceof_cause(self):
        """Return an exception that is both a RayTaskError and the cause's type,
        so `except UserError` works across the task boundary."""
        if self.cause is None:
            return self
        cause_cls = type(self.cause)
        if cause_cls is RayTaskError or not issubclass(cause_cls, Exception):
            return self
        name = f"RayTaskError({cause_cls.__name__})"
        cls = type(name, (RayTaskError, cause_cls), {})
        err = cls.__new__(cls)
        # Initialize fields directly: RayTaskError.__init__'s super() call
        # would resolve through the dual class's MRO into the CAUSE's
        # __init__ (e.g. RayActorError swallowing the message as actor_id),
        # replacing the remote traceback with the cause's default text.
        err.function_name = self.function_name
        err.traceback_str = self.traceback_str
        err.cause = self.cause
        Exception.__init__(
            err, f"Task {self.function_name} failed:\n{self.traceback_str}")
        return err


def _rebuild_task_error(function_name, traceback_str, cause):
    return RayTaskError(function_name, traceback_str, cause)


class RayActorError(RayTpuError):
    """The actor died before or during method execution."""

    def __init__(self, actor_id=None, message: str = "The actor died unexpectedly."):
        self.actor_id = actor_id
        self._message = message
        super().__init__(message)

    def __reduce__(self):
        # Default Exception pickling would pass args[0] (the message) as
        # actor_id on rebuild, silently resetting the message to the
        # default — keep both fields explicit.
        return (type(self), (self.actor_id, self._message))


class ActorDiedError(RayActorError):
    pass


class ActorUnavailableError(RayActorError):
    """The actor is temporarily unreachable (restarting or network partition)."""


class TaskCancelledError(RayTpuError):
    def __init__(self, task_id=None):
        self.task_id = task_id
        super().__init__(f"Task {task_id} was cancelled.")


class ObjectLostError(RayTpuError):
    """The object's value was lost from the store and could not be reconstructed."""

    def __init__(self, object_id=None, message: str | None = None):
        self.object_id = object_id
        super().__init__(message or f"Object {object_id} was lost.")


class ObjectReconstructionFailedError(ObjectLostError):
    pass


class OwnerDiedError(ObjectLostError):
    """The worker that owned this object died, so the value is unrecoverable."""


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died (OOM kill, segfault, ...)."""


class OutOfMemoryError(WorkerCrashedError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class RaySystemError(RayTpuError):
    """Internal framework failure (control plane / store)."""


class CollectiveError(RayTpuError):
    """A host-collective operation aborted.

    Raised on every surviving rank when a group member dies mid-operation
    (``dead_ranks`` maps rank -> reason), when the group was destroyed
    under the caller, or when an operation stalls past
    ``collective_stall_timeout_s`` with no progress.
    """

    def __init__(self, message: str, dead_ranks=None, group_name=None):
        self.dead_ranks = dict(dead_ranks or {})
        self.group_name = group_name
        super().__init__(message)

    def __reduce__(self):
        return (type(self), (self.args[0], self.dead_ranks, self.group_name))
