"""Experimental utilities (reference `python/ray/experimental/`)."""

from ray_tpu.experimental import internal_kv, tqdm_ray
from ray_tpu.experimental.dynamic_resources import set_resource

__all__ = ["internal_kv", "set_resource", "tqdm_ray"]
