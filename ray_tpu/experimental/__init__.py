"""Experimental utilities (reference `python/ray/experimental/`)."""

from ray_tpu.experimental import internal_kv

__all__ = ["internal_kv"]
