"""Dynamic custom resources (reference
`python/ray/experimental/dynamic_resources.py`): change a node's custom
resource capacity at runtime — tasks queued on the resource dispatch as
soon as capacity appears, without restarting the node.
"""

from __future__ import annotations

from typing import Optional


def set_resource(resource_name: str, capacity: float,
                 node_id: Optional[str] = None) -> None:
    """Set `resource_name`'s TOTAL capacity on a node (default: the
    caller's node). capacity=0 deletes the resource. Built-in resources
    (CPU/TPU/memory) cannot be overridden."""
    import ray_tpu
    from ray_tpu.core.ids import NodeID

    runtime = ray_tpu._global_runtime
    if runtime is None:
        raise RuntimeError("ray_tpu.init() first")
    nid = (NodeID.from_hex(node_id) if isinstance(node_id, str)
           else node_id) or runtime.node_id
    runtime.gcs.call("set_node_resource",
                     {"resource_name": resource_name,
                      "capacity": float(capacity), "node_id": nid},
                     timeout=15)
