"""Internal KV client: direct access to the GCS key-value store.

Equivalent of the reference's `python/ray/experimental/internal_kv.py`
(`_internal_kv_get/put/del/list/exists`) — the same store that backs
function distribution, serve controller state, and runtime_env packages.
"""

from __future__ import annotations

from typing import List, Optional


def _gcs():
    import ray_tpu

    return ray_tpu._require_runtime().gcs


def _key(k) -> bytes:
    return k.encode() if isinstance(k, str) else bytes(k)


def _internal_kv_initialized() -> bool:
    import ray_tpu

    return ray_tpu.is_initialized()


def _internal_kv_put(key, value, overwrite: bool = True,
                     namespace: str = "") -> bool:
    """Returns True if the key already existed (matching the reference)."""
    val = value.encode() if isinstance(value, str) else bytes(value)
    resp = _gcs().call("kv_put", {"namespace": namespace, "key": _key(key),
                                  "value": val, "overwrite": overwrite})
    return bool(resp.get("existed", not resp["added"]))


def _internal_kv_get(key, namespace: str = "") -> Optional[bytes]:
    return _gcs().call("kv_get", {"namespace": namespace,
                                  "key": _key(key)})["value"]


def _internal_kv_exists(key, namespace: str = "") -> bool:
    return _gcs().call("kv_exists", {"namespace": namespace,
                                     "key": _key(key)})["exists"]


def _internal_kv_del(key, del_by_prefix: bool = False,
                     namespace: str = "") -> int:
    return _gcs().call("kv_del", {"namespace": namespace, "key": _key(key),
                                  "prefix": del_by_prefix})["deleted"]


def _internal_kv_list(prefix, namespace: str = "") -> List[bytes]:
    return _gcs().call("kv_keys", {"namespace": namespace,
                                   "prefix": _key(prefix)})["keys"]
