"""Distributed progress bars (reference
`python/ray/experimental/tqdm_ray.py`): a tqdm-shaped API usable inside
tasks/actors whose progress lines flow to the driver through the
existing worker log streaming — no terminal fighting between dozens of
remote processes, no tqdm dependency.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Iterable, Optional


class tqdm:  # noqa: N801 — mirrors the tqdm API it substitutes
    """Rate-limited textual progress; safe in any worker process."""

    MIN_INTERVAL_S = 0.5

    def __init__(self, iterable: Optional[Iterable] = None, *,
                 desc: str = "", total: Optional[int] = None,
                 position: int = 0, flush_interval_s: Optional[float] = None):
        self._iterable = iterable
        self.desc = desc or "progress"
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self.n = 0
        self._start = time.monotonic()
        self._last_print = 0.0
        self._interval = (self.MIN_INTERVAL_S if flush_interval_s is None
                          else flush_interval_s)
        self._closed = False

    def __iter__(self):
        if self._iterable is None:
            raise TypeError("tqdm(...) created without an iterable")
        try:
            for item in self._iterable:
                yield item
                self.update(1)
        finally:
            self.close()

    def update(self, n: int = 1) -> None:
        self.n += n
        now = time.monotonic()
        if now - self._last_print >= self._interval:
            self._last_print = now
            self._emit()

    def set_description(self, desc: str) -> None:
        self.desc = desc

    def _emit(self, final: bool = False) -> None:
        elapsed = max(time.monotonic() - self._start, 1e-9)
        rate = self.n / elapsed
        if self.total:
            pct = 100.0 * self.n / self.total
            line = (f"[{self.desc} pid={os.getpid()}] "
                    f"{self.n}/{self.total} ({pct:.0f}%) "
                    f"[{rate:.1f} it/s]")
        else:
            line = (f"[{self.desc} pid={os.getpid()}] {self.n} "
                    f"[{rate:.1f} it/s]")
        if final:
            line += " done"
        # stdout is captured by the worker's log streamer and printed on
        # the driver — one line per interval instead of a live bar.
        print(line, file=sys.stdout, flush=True)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._emit(final=True)

    def __enter__(self) -> "tqdm":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
