"""ray_tpu.inference — continuous-batching LLM serving engine.

The inference plane next to the transfer (PR 1) and collective (PR 2)
planes: a paged KV-cache block manager (`kv_cache`), an iteration-level
scheduler that re-forms the batch every decode step (`engine`), and a
Serve deployment streaming tokens as they are produced (`api`).

    from ray_tpu.inference import LLMServer
    handle = serve.run(LLMServer.bind("tiny"))
    for event in handle.options(stream=True).stream.remote(
            {"ids": [1, 2, 3], "max_new_tokens": 16}):
        ...
"""

from ray_tpu.inference.adapters import AdapterLoadError, AdapterManager
from ray_tpu.inference.engine import (
    EngineConfig,
    EngineLoop,
    InferenceEngine,
    Request,
)
from ray_tpu.inference.kv_cache import BlockManager

__all__ = [
    "AdapterLoadError",
    "AdapterManager",
    "BlockManager",
    "EngineConfig",
    "EngineLoop",
    "InferenceEngine",
    "LLMServer",
    "Request",
]


def __getattr__(name):
    # LLMServer pulls in ray_tpu.serve; keep the core engine importable
    # without the serving stack (and without a cluster).
    if name == "LLMServer":
        from ray_tpu.inference.api import LLMServer

        return LLMServer
    raise AttributeError(name)
