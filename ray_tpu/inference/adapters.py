"""LRU-resident LoRA adapter banks: many models on one engine.

A model-multiplexed replica (docs/MULTITENANCY.md) hosts several
LoRA-style adapters that share ONE paged KV arena and ONE compiled
program set. This module owns the residency bookkeeping: which
`model_id` occupies which bank row, LRU eviction when a new adapter
needs a row, and the host->device bank materialization the engine's
step programs consume.

The banks are fixed-shape per-layer arrays ([n_rows, ...], row 0 the
zero identity) so adapter load/evict is pure data movement — the jit
cache key (shape, dtype, sharding) never changes, which is what the
compile counters prove in `bench_zoo` and the multiplex tests. Rows
holding adapters with live sequences are pinned: eviction can never
yank weights out from under a mid-flight generation.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.models.llama import lora_bank_shapes


class AdapterLoadError(ValueError):
    """The adapter cannot become resident (unknown id, or every row is
    pinned by live sequences)."""


class AdapterManager:
    """Residency + banks for one engine. Single-threaded by contract:
    every call happens under the engine lock (submission/step paths)."""

    def __init__(self, model_cfg, max_adapters: int, rank: int,
                 mesh=None):
        import numpy as np

        if max_adapters < 1:
            raise ValueError("max_adapters must be >= 1 when multiplexing")
        if rank < 1:
            raise ValueError("lora rank must be >= 1")
        self._cfg = model_cfg
        self.max_adapters = max_adapters
        self.rank = rank
        self._mesh = mesh
        n_rows = max_adapters + 1   # row 0 = identity (never assigned)
        import jax.numpy as jnp

        dt = jnp.dtype(model_cfg.dtype)
        self._host: List[Tuple] = [
            tuple(np.zeros(shape, dtype=dt)
                  for shape in lora_bank_shapes(model_cfg, n_rows, rank))
            for _ in range(model_cfg.n_layer)]
        self._rows: Dict[str, int] = {}        # model_id -> bank row
        self._last_used: Dict[str, float] = {}  # model_id -> monotonic
        self._free_rows = list(range(n_rows - 1, 0, -1))
        self._device_banks = None               # cache, dropped on change
        self._shardings = None
        if mesh is not None:
            from ray_tpu.models.llama import lora_bank_shardings

            self._shardings = lora_bank_shardings(model_cfg, mesh)
        self.loads = 0
        self.evictions = 0
        self.hits = 0

    # ------------------------------------------------------------ queries

    def resident(self) -> List[str]:
        return sorted(self._rows)

    def row_of(self, model_id: str) -> Optional[int]:
        return self._rows.get(model_id)

    # ---------------------------------------------------------- residency

    def ensure(self, model_id: str,
               loader: Callable[[str], list],
               pinned_rows=()) -> int:
        """Make `model_id` resident and return its bank row. `loader`
        produces the per-layer (aq, bq, ao, bo) rows on a miss (e.g.
        `make_adapter_weights` from the adapter's registered seed); LRU
        evicts the least-recently-used unpinned adapter when the bank is
        full. Raises AdapterLoadError when nothing can be evicted."""
        row = self._rows.get(model_id)
        if row is not None:
            self.hits += 1
            self._last_used[model_id] = time.monotonic()
            return row
        # Load BEFORE evicting/claiming a row: a failing loader (unknown
        # id, bad shapes) must leave residency untouched — no leaked row,
        # no victim evicted for nothing.
        weights = loader(model_id)
        if not self._free_rows:
            victim = self._pick_victim(pinned_rows)
            if victim is None:
                raise AdapterLoadError(
                    f"cannot load adapter {model_id!r}: all "
                    f"{self.max_adapters} bank rows are pinned by live "
                    "sequences (raise max_adapters)")
            self._evict(victim)
        row = self._free_rows.pop()
        try:
            self._write_row(row, weights)
        except BaseException:
            self._zero_row(row)
            self._free_rows.append(row)
            raise
        self._rows[model_id] = row
        self._last_used[model_id] = time.monotonic()
        self.loads += 1
        self._device_banks = None
        return row

    def evict(self, model_id: str) -> bool:
        """Explicit eviction (tests / admin); False when not resident."""
        if model_id not in self._rows:
            return False
        self._evict(model_id)
        self._device_banks = None
        return True

    def _pick_victim(self, pinned_rows) -> Optional[str]:
        pinned = set(pinned_rows)
        candidates = [(self._last_used[mid], mid)
                      for mid, row in self._rows.items()
                      if row not in pinned]
        if not candidates:
            return None
        return min(candidates)[1]

    def _evict(self, model_id: str) -> None:
        row = self._rows.pop(model_id)
        self._last_used.pop(model_id, None)
        self._zero_row(row)
        self._free_rows.append(row)
        self.evictions += 1

    def _write_row(self, row: int, weights) -> None:
        if len(weights) != len(self._host):
            raise AdapterLoadError(
                f"adapter has {len(weights)} layers; model has "
                f"{len(self._host)}")
        for layer, rows in zip(self._host, weights):
            for bank, w in zip(layer, rows):
                if bank[row].shape != w.shape:
                    raise AdapterLoadError(
                        f"adapter row shape {w.shape} != bank row "
                        f"{bank[row].shape} (rank mismatch?)")
                bank[row] = w

    def _zero_row(self, row: int) -> None:
        for layer in self._host:
            for bank in layer:
                bank[row] = 0

    # -------------------------------------------------------------- banks

    def device_banks(self):
        """Per-layer [(aq, bq, ao, bo)] device arrays for the step
        programs, cached until residency changes. Placed with the SAME
        shardings every time (tp: B output dims split with their heads)
        so a reload is invisible to the jit cache."""
        if self._device_banks is None:
            import jax

            if self._shardings is not None:
                self._device_banks = [
                    tuple(jax.device_put(bank, s)
                          for bank, s in zip(layer, self._shardings))
                    for layer in self._host]
            else:
                self._device_banks = [
                    tuple(jax.device_put(bank) for bank in layer)
                    for layer in self._host]
        return self._device_banks

    def stats(self) -> Dict[str, object]:
        return {
            "resident": self.resident(),
            "capacity": self.max_adapters,
            "loads": self.loads,
            "evictions": self.evictions,
            "hits": self.hits,
        }
