"""Serve integration for the continuous-batching engine.

`LLMServer` is a `@serve.deployment` hosting one `InferenceEngine` per
replica (the replica's actor owns the chip; the engine thread owns the
jitted step programs). Two entry points:

- `__call__` / `generate`: complete the whole generation, return
  ``{"ids": [...]}`` — wire-compatible with the `LlamaSampler` example.
- `stream`: an async generator yielding one event per produced token;
  the existing replica/handle/proxy stream plumbing carries them to
  Python callers (``handle.options(stream=True)``) and HTTP clients
  (chunked JSON lines) as they are emitted — time-to-first-token is one
  scheduler step, not one full generation.

The replica exports the engine's queue depth through the
``__serve_metrics__`` hook, so the controller's autoscaler sees queued
requests (not just in-flight RPCs) and scales replicas on real backlog.
``__serve_shutdown__`` stops the engine thread at replica teardown.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

from ray_tpu import serve
from ray_tpu.inference.engine import EngineConfig, EngineLoop, InferenceEngine


def _parse(payload: Optional[Dict[str, Any]], default_new: int):
    payload = payload or {}
    ids = [int(t) for t in payload.get("ids", [])] or [0]
    max_new = max(1, int(payload.get("max_new_tokens", default_new)))
    model_id = payload.get("model_id") or payload.get("model")
    slo = payload.get("slo_class") or payload.get("slo")
    return (ids, max_new,
            (str(model_id) if model_id is not None else None),
            (str(slo) if slo is not None else None))


@serve.deployment(max_concurrent_queries=64)
class LLMServer:
    """Continuous-batching LLM deployment.

    Request: ``{"ids": [int, ...], "max_new_tokens": int}``;
    response: ``{"ids": [prompt + generated]}`` (generate) or a stream of
    ``{"token": int}`` events followed by ``{"done": true, "ids": [...]}``
    (stream).
    """

    def __init__(self, model_size: str = "tiny",
                 max_model_len: int = 256,
                 default_new_tokens: int = 16,
                 engine_config: Optional[Dict[str, Any]] = None,
                 adapters: Optional[Dict[str, Dict[str, Any]]] = None,
                 max_resident_adapters: int = 0):
        kwargs = dict(engine_config or {})
        kwargs.setdefault("model_size", model_size)
        kwargs.setdefault("max_model_len", max_model_len)
        # Model multiplexing: `adapters` registers the replica's servable
        # LoRA models ({model_id: {"seed": int, "rank": r, "scale": s}}).
        # Weights are DERIVED (deterministically, from the seed) on
        # demand, loaded into the shared bank LRU-style — a respawned
        # replica reloads an adapter the moment a request names it, bit-
        # identical to before the crash. max_resident_adapters bounds
        # bank rows (default: all registered adapters resident at once).
        self._adapter_specs = {str(k): dict(v or {})
                               for k, v in (adapters or {}).items()}
        if self._adapter_specs:
            ranks = {int(s.get("rank", 8))
                     for s in self._adapter_specs.values()}
            if len(ranks) > 1:
                raise ValueError(
                    f"all adapters of a replica share one bank rank; "
                    f"got {sorted(ranks)}")
            kwargs.setdefault("max_adapters",
                              max_resident_adapters
                              or len(self._adapter_specs))
            kwargs.setdefault("lora_rank", ranks.pop())
        self._default_new = default_new_tokens
        self._config = EngineConfig(**kwargs)
        # Sharded replica groups: when this replica is a gang rank the
        # shard context was activated before this ctor ran; the gang's
        # tp mesh turns on the engine's tensor-parallel path (params and
        # the paged KV arena shard over the mesh, same seed -> same
        # weights as an unsharded replica).
        from ray_tpu import shardgroup

        self._engine = InferenceEngine(self._config,
                                       mesh=shardgroup.current_mesh())
        if self._adapter_specs:
            self._engine.register_adapter_source(self._load_adapter)
        self._loop = EngineLoop(self._engine)

    def _load_adapter(self, model_id: str):
        """Engine adapter source: spec -> deterministic weights (the
        parity and chaos tests depend on seed => same bytes)."""
        from ray_tpu.models.llama import make_adapter_weights

        spec = self._adapter_specs.get(model_id)
        if spec is None:
            raise ValueError(
                f"unknown model {model_id!r} (registered: "
                f"{sorted(self._adapter_specs)})")
        return make_adapter_weights(
            self._engine._model.config,
            rank=int(spec.get("rank", 8)),
            seed=int(spec.get("seed", 0)),
            scale=float(spec.get("scale", 0.05)))

    # ------------------------------------------------------------ complete

    async def __call__(self, payload=None):
        # HTTP clients reach methods only through __call__: a
        # ``"stream": true`` field switches to the token stream (the
        # replica pumps the returned async generator, the proxy relays
        # it as chunked JSON lines).
        if isinstance(payload, dict) and payload.get("stream"):
            return self.stream(payload)
        return await self.generate(payload)

    async def generate(self, payload=None):
        ids, max_new, model_id, slo = _parse(payload, self._default_new)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()

        def on_finish(req):
            def _resolve():
                if fut.done():
                    return
                if req.error:
                    fut.set_exception(RuntimeError(req.error))
                else:
                    fut.set_result(None)
            loop.call_soon_threadsafe(_resolve)

        req = self._loop.submit(ids, max_new, on_finish=on_finish,
                                model_id=model_id, slo_class=slo)
        try:
            await fut
        except asyncio.CancelledError:
            # Caller abandoned the request: release its slot and blocks.
            self._engine.cancel(req.request_id)
            raise
        return {"ids": list(req.prompt) + list(req.generated)}

    # -------------------------------------------------------------- stream

    async def stream(self, payload=None):
        """Async generator: one ``{"token": t}`` per produced token, then
        ``{"done": True, "ids": [...]}`` — replica pumps it through the
        stream queue, the proxy relays chunked JSON lines, handles iterate
        it with ``options(stream=True)``."""
        ids, max_new, model_id, slo = _parse(payload, self._default_new)
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()

        def on_token(req, token):
            loop.call_soon_threadsafe(queue.put_nowait, ("token", token))

        def on_finish(req):
            loop.call_soon_threadsafe(queue.put_nowait, ("end", req))

        req = self._loop.submit(ids, max_new, on_token=on_token,
                                on_finish=on_finish, model_id=model_id,
                                slo_class=slo)
        try:
            while True:
                kind, item = await queue.get()
                if kind == "token":
                    yield {"token": item}
                else:
                    if item.error:
                        raise RuntimeError(item.error)
                    yield {"done": True,
                           "ids": list(item.prompt) + list(item.generated)}
                    return
        finally:
            # Client gone mid-stream (the replica's pump was cancelled /
            # the generator closed): abort the engine request so its
            # batch slot and KV blocks go back to live traffic instead
            # of decoding to budget for nobody. No-op when finished.
            self._engine.cancel(req.request_id)

    # ------------------------------------------------------------- control

    def metrics(self, _=None) -> Dict[str, Any]:
        return self._engine.stats()

    def __serve_metrics__(self) -> Dict[str, Any]:
        """Autoscaling signal (replica merges this into its stats): queued
        requests count toward pressure exactly like in-flight ones. For
        multiplexed replicas the resident adapter ids ride along — the
        controller pushes them in the routing table so routers prefer a
        replica that already holds the request's adapter."""
        stats = self._engine.stats()
        out = {"queue_depth": stats["queue_depth"],
               "running": stats["running"],
               "tokens_per_sec": stats["tokens_per_sec"],
               "prefix_hit_rate": stats["prefix_cache"].get("hit_rate", 0.0)}
        adapters = stats.get("adapters")
        if adapters is not None:
            out["adapters"] = adapters["resident"]
        return out

    def __serve_shutdown__(self) -> None:
        self._loop.stop()
