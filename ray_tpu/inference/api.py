"""Serve integration for the continuous-batching engine.

`LLMServer` is a `@serve.deployment` hosting one `InferenceEngine` per
replica (the replica's actor owns the chip; the engine thread owns the
jitted step programs). Two entry points:

- `__call__` / `generate`: complete the whole generation, return
  ``{"ids": [...]}`` — wire-compatible with the `LlamaSampler` example.
- `stream`: an async generator yielding one event per produced token;
  the existing replica/handle/proxy stream plumbing carries them to
  Python callers (``handle.options(stream=True)``) and HTTP clients
  (chunked JSON lines) as they are emitted — time-to-first-token is one
  scheduler step, not one full generation.

The replica exports the engine's queue depth through the
``__serve_metrics__`` hook, so the controller's autoscaler sees queued
requests (not just in-flight RPCs) and scales replicas on real backlog.
``__serve_shutdown__`` stops the engine thread at replica teardown.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, Optional

from ray_tpu import serve
from ray_tpu.inference.engine import EngineConfig, EngineLoop, InferenceEngine


def _parse(payload: Optional[Dict[str, Any]], default_new: int):
    payload = payload or {}
    ids = [int(t) for t in payload.get("ids", [])] or [0]
    max_new = max(1, int(payload.get("max_new_tokens", default_new)))
    return ids, max_new


@serve.deployment(max_concurrent_queries=64)
class LLMServer:
    """Continuous-batching LLM deployment.

    Request: ``{"ids": [int, ...], "max_new_tokens": int}``;
    response: ``{"ids": [prompt + generated]}`` (generate) or a stream of
    ``{"token": int}`` events followed by ``{"done": true, "ids": [...]}``
    (stream).
    """

    def __init__(self, model_size: str = "tiny",
                 max_model_len: int = 256,
                 default_new_tokens: int = 16,
                 engine_config: Optional[Dict[str, Any]] = None):
        kwargs = dict(engine_config or {})
        kwargs.setdefault("model_size", model_size)
        kwargs.setdefault("max_model_len", max_model_len)
        self._default_new = default_new_tokens
        self._config = EngineConfig(**kwargs)
        # Sharded replica groups: when this replica is a gang rank the
        # shard context was activated before this ctor ran; the gang's
        # tp mesh turns on the engine's tensor-parallel path (params and
        # the paged KV arena shard over the mesh, same seed -> same
        # weights as an unsharded replica).
        from ray_tpu import shardgroup

        self._engine = InferenceEngine(self._config,
                                       mesh=shardgroup.current_mesh())
        self._loop = EngineLoop(self._engine)

    # ------------------------------------------------------------ complete

    async def __call__(self, payload=None):
        # HTTP clients reach methods only through __call__: a
        # ``"stream": true`` field switches to the token stream (the
        # replica pumps the returned async generator, the proxy relays
        # it as chunked JSON lines).
        if isinstance(payload, dict) and payload.get("stream"):
            return self.stream(payload)
        return await self.generate(payload)

    async def generate(self, payload=None):
        ids, max_new = _parse(payload, self._default_new)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()

        def on_finish(req):
            def _resolve():
                if fut.done():
                    return
                if req.error:
                    fut.set_exception(RuntimeError(req.error))
                else:
                    fut.set_result(None)
            loop.call_soon_threadsafe(_resolve)

        req = self._loop.submit(ids, max_new, on_finish=on_finish)
        try:
            await fut
        except asyncio.CancelledError:
            # Caller abandoned the request: release its slot and blocks.
            self._engine.cancel(req.request_id)
            raise
        return {"ids": list(req.prompt) + list(req.generated)}

    # -------------------------------------------------------------- stream

    async def stream(self, payload=None):
        """Async generator: one ``{"token": t}`` per produced token, then
        ``{"done": True, "ids": [...]}`` — replica pumps it through the
        stream queue, the proxy relays chunked JSON lines, handles iterate
        it with ``options(stream=True)``."""
        ids, max_new = _parse(payload, self._default_new)
        loop = asyncio.get_running_loop()
        queue: asyncio.Queue = asyncio.Queue()

        def on_token(req, token):
            loop.call_soon_threadsafe(queue.put_nowait, ("token", token))

        def on_finish(req):
            loop.call_soon_threadsafe(queue.put_nowait, ("end", req))

        req = self._loop.submit(ids, max_new, on_token=on_token,
                                on_finish=on_finish)
        try:
            while True:
                kind, item = await queue.get()
                if kind == "token":
                    yield {"token": item}
                else:
                    if item.error:
                        raise RuntimeError(item.error)
                    yield {"done": True,
                           "ids": list(item.prompt) + list(item.generated)}
                    return
        finally:
            # Client gone mid-stream (the replica's pump was cancelled /
            # the generator closed): abort the engine request so its
            # batch slot and KV blocks go back to live traffic instead
            # of decoding to budget for nobody. No-op when finished.
            self._engine.cancel(req.request_id)

    # ------------------------------------------------------------- control

    def metrics(self, _=None) -> Dict[str, Any]:
        return self._engine.stats()

    def __serve_metrics__(self) -> Dict[str, Any]:
        """Autoscaling signal (replica merges this into its stats): queued
        requests count toward pressure exactly like in-flight ones."""
        stats = self._engine.stats()
        return {"queue_depth": stats["queue_depth"],
                "running": stats["running"],
                "tokens_per_sec": stats["tokens_per_sec"]}

    def __serve_shutdown__(self) -> None:
        self._loop.stop()
