"""Continuous-batching inference engine (Orca-style iteration scheduling).

The serving batch is re-formed every decode step instead of every request:
finished sequences leave their batch slot immediately, queued requests are
admitted into freed slots, and long prompts prefill in fixed-size chunks
interleaved with decode steps so token emission never stalls behind a new
arrival. K/V lives in a paged arena (`kv_cache.BlockManager` +
`models/llama.py:decode_paged`); when the arena runs out of blocks the
engine preempts the lowest-priority sequence — frees its blocks and
re-queues it for recompute — so the answer to memory pressure is degraded
latency, never an OOM.

Two jitted programs serve every request mix, each compiled exactly once:

- prefill: [1, prefill_chunk] tokens of one sequence (padded chunk),
- decode:  [batch_slots, 1] — one token for every running slot.

Speculative decoding (spec_decode_draft_len > 0) swaps the decode step
for three more fixed-shape programs — draft prefill [1, chunk], propose
(k+1 scanned draft steps), verify [batch_slots, k+1] — still compiled
exactly once each; greedy verification makes the emitted tokens
identical to plain decoding, whatever the draft proposes.

A radix prefix cache (prefix_cache_enabled, continuous scheduling)
keeps finished sequences' full-block KV prefixes refcounted in the
arena; a new request adopts its longest cached match and prefills only
the tail. Cached blocks are reclaimed LRU-by-leaf under pressure before
any live sequence is preempted.

All shapes are static (batch slots, chunk width, block-table width), so
the engine's per-step work is argument values, never new programs; the
stats track compile counts to prove it — including on the cached path,
which reuses the same programs with fewer invocations.

The engine core is synchronous and single-threaded (`step()`); tests drive
it directly. `EngineLoop` runs it on a background thread and is what the
Serve deployment (`api.py`) uses; token/finish callbacks are fired outside
the engine lock so they may bounce into an asyncio loop safely.

`scheduling="static"` emulates the request-level `@serve.batch` baseline
(gang admission, batch drains at the speed of its longest member, results
delivered only when the whole gang finishes) through the same compute
path — `bench.py:bench_inference` uses it so the comparison is pure
scheduling policy.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.inference.kv_cache import BlockManager, RadixPrefixCache
from ray_tpu.observability import tracing as _tracing

logger = logging.getLogger(__name__)

# Request states.
WAITING = "WAITING"      # queued (fresh, or preempted awaiting recompute)
PREFILL = "PREFILL"      # in a slot, prompt (+ recomputed tokens) mid-chunk
DECODE = "DECODE"        # in a slot, emitting one token per step
FINISHED = "FINISHED"
FAILED = "FAILED"
_DONE_HOLD = "DONE_HOLD"  # static mode: finished but holding its gang slot


@dataclass(frozen=True)
class EngineConfig:
    model_size: str = "tiny"        # LlamaConfig preset (tiny/small/7b)
    max_model_len: int = 256        # positions preset for tiny
    batch_slots: int = 4            # fixed decode batch width
    block_size: int = 16            # KV tokens per block
    num_blocks: int = 64            # arena size (incl. trash block 0)
    max_blocks_per_seq: int = 8     # block-table width => max context
    prefill_chunk: int = 16         # prompt tokens per prefill step
    eos_id: Optional[int] = None    # stop token (None = budget only)
    use_jit: bool = True            # False = eager smoke mode
    scheduling: str = "continuous"  # or "static" (@serve.batch emulation)
    # Model multiplexing (docs/MULTITENANCY.md): >0 hosts that many
    # LoRA-style adapters on this engine — one shared paged arena, the
    # SAME two compiled programs (adapter routing is a per-row index
    # argument), per-replica LRU residency. 0 = classic single model.
    max_adapters: int = 0
    lora_rank: int = 8
    # Round-3 knobs (docs/INFERENCE.md). None = resolve from the global
    # flag table at engine construction, so deployments pick them up via
    # RAY_TPU_* env vars / _system_config without a config plumb-through.
    prefix_cache_enabled: Optional[bool] = None
    spec_decode_draft_len: Optional[int] = None
    slo_default_class: Optional[str] = None
    slo_interactive_reserved_slots: Optional[int] = None

    @property
    def max_context(self) -> int:
        return self.max_blocks_per_seq * self.block_size


@dataclass
class Request:
    request_id: str
    prompt: List[int]
    max_new_tokens: int
    arrival: int                      # admission priority (lower = older)
    on_token: Optional[Callable] = None    # (req, token) per emitted token
    on_finish: Optional[Callable] = None   # (req) once, FINISHED or FAILED
    state: str = WAITING
    generated: List[int] = field(default_factory=list)
    error: Optional[str] = None
    preemptions: int = 0
    submitted_at: float = 0.0
    admitted_at: Optional[float] = None    # first batch-slot admission
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # Trace context captured at submission: the engine's queue/prefill/
    # decode phase spans (a TTFT decomposition) re-parent to it.
    trace_ctx: Optional[Dict] = None
    # Model multiplexing: which adapter this request routes through
    # (None = base model, bank row 0 identity).
    model_id: Optional[str] = None
    adapter_row: int = 0
    # SLO class ("interactive" | "batch"): admission/prefill priority and
    # preemption victim order.
    slo_class: str = "interactive"
    # Prefix-cache accounting: prompt tokens whose KV was adopted from
    # the radix cache instead of prefilled (across all admissions).
    cached_tokens: int = 0
    # Scheduler-internal:
    slot: Optional[int] = None
    processed: int = 0                # tokens written into the KV cache
    cur_token: Optional[int] = None   # next decode input
    _held_emits: List[tuple] = field(default_factory=list)
    _pinned_node: Any = None          # radix node pinned while scheduled

    @property
    def total_to_prefill(self) -> int:
        # Recompute after preemption replays prompt + already-generated.
        return len(self.prompt) + len(self.generated)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, FAILED)


class InferenceEngine:
    """Synchronous engine core; every public method takes the engine lock.

    `model`/`params` may be injected (tests share one tiny checkpoint with
    their reference loop); by default the config's Llama preset is built
    with randomly initialized weights, matching the sampler examples.
    """

    def __init__(self, config: EngineConfig, model=None, params=None,
                 mesh=None, draft_model=None, draft_params=None):
        import jax
        import jax.numpy as jnp

        from ray_tpu.core.config import GLOBAL_CONFIG
        from ray_tpu.models.llama import (
            Llama,
            LlamaConfig,
            arena_sharding,
            make_paged_arena,
            shard_params_tp,
        )

        cfg = config
        if cfg.scheduling not in ("continuous", "static"):
            raise ValueError(f"unknown scheduling {cfg.scheduling!r}")
        if cfg.max_blocks_per_seq * cfg.block_size < cfg.prefill_chunk:
            raise ValueError("prefill_chunk exceeds the per-seq context")
        self.config = cfg
        # Round-3 knobs: explicit config wins, else the global flag table.
        self._draft_len = int(
            cfg.spec_decode_draft_len
            if cfg.spec_decode_draft_len is not None
            else GLOBAL_CONFIG.spec_decode_draft_len)
        self._slo_default = str(
            cfg.slo_default_class if cfg.slo_default_class is not None
            else GLOBAL_CONFIG.slo_default_class)
        if self._slo_default not in ("interactive", "batch"):
            raise ValueError(
                f"unknown slo_default_class {self._slo_default!r}")
        self._slo_reserved = min(
            cfg.batch_slots - 1,
            max(0, int(cfg.slo_interactive_reserved_slots
                       if cfg.slo_interactive_reserved_slots is not None
                       else GLOBAL_CONFIG.slo_interactive_reserved_slots)))
        prefix_enabled = (
            cfg.prefix_cache_enabled if cfg.prefix_cache_enabled is not None
            else bool(GLOBAL_CONFIG.prefix_cache_enabled))
        if model is None:
            mc = {"tiny": LlamaConfig.tiny(seq=cfg.max_model_len),
                  "small": LlamaConfig.small(),
                  "7b": LlamaConfig.llama7b()}[cfg.model_size]
            model = Llama(mc)
            params = jax.jit(lambda: model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((1, 8), jnp.int32)))()
        # Tensor-parallel serving (docs/SHARDED.md): with a mesh, params
        # are placed into their tp NamedShardings (heads/mlp/vocab split
        # over the "tp" axis) and the paged arena shards its kv-head dim
        # WITH the heads — the jitted step programs below then compile to
        # partitioned XLA with no code change here (GSPMD does the rest).
        self._mesh = mesh
        self._tp = 1
        if mesh is not None:
            from ray_tpu.models.llama import _mesh_tp

            self._tp = _mesh_tp(mesh)
            params = shard_params_tp(model, params, mesh)
        self._model = model
        self._params = params
        self._arena_sharding = (arena_sharding(model.config, mesh)
                                if mesh is not None else None)
        self._bm = BlockManager(cfg.num_blocks, cfg.block_size)
        self._arenas = make_paged_arena(model.config, cfg.num_blocks,
                                        cfg.block_size,
                                        sharding=self._arena_sharding)
        # Radix prefix cache (continuous scheduling only: static gangs
        # hold finished members' blocks for the drain, which fights the
        # donate-to-cache lifecycle and the baseline it emulates never
        # had prefix reuse anyway).
        self._prefix: Optional[RadixPrefixCache] = None
        if prefix_enabled and cfg.scheduling == "continuous":
            self._prefix = RadixPrefixCache(self._bm)
        # Speculative decoding: the draft shares the target's BLOCK
        # TABLES (host bookkeeping) but writes its own arenas — same
        # geometry, so one table addresses both. Default draft: the
        # TRUNCATED target (its first n_layer//2 blocks plus its embed/
        # final-norm/lm-head, parameters shared by reference) — an
        # early-exit draft that agrees with the target on easy tokens
        # for free. Greedy verify makes the output independent of draft
        # quality either way; a better draft just accepts more.
        self._draft_model = None
        self._draft_params = None
        self._draft_arenas = None
        self._draft_arena_sharding = None
        if self._draft_len > 0:
            if draft_model is None:
                import dataclasses as _dc

                dcfg = _dc.replace(model.config,
                                   n_layer=max(1, model.config.n_layer // 2))
                draft_model = Llama(dcfg)
                inner = params["params"] if "params" in params else params
                dp = {k: inner[k]
                      for k in ("embed", "final_norm", "lm_head")}
                for i in range(dcfg.n_layer):
                    dp[f"layer_{i}"] = inner[f"layer_{i}"]
                draft_params = {"params": dp}
            if mesh is not None:
                draft_params = shard_params_tp(draft_model, draft_params,
                                               mesh)
                self._draft_arena_sharding = arena_sharding(
                    draft_model.config, mesh)
            self._draft_model = draft_model
            self._draft_params = draft_params
            self._draft_arenas = make_paged_arena(
                draft_model.config, cfg.num_blocks, cfg.block_size,
                sharding=self._draft_arena_sharding)
        # Model multiplexing: the adapter bank + residency bookkeeping.
        # `adapter_source(model_id) -> per-layer rows` is registered by
        # the deployment (api.py) so a miss loads on demand.
        self._adapters = None
        self._adapter_source = None
        if cfg.max_adapters > 0:
            from ray_tpu.inference.adapters import AdapterManager

            self._adapters = AdapterManager(model.config, cfg.max_adapters,
                                            cfg.lora_rank, mesh=mesh)
        self._slots: List[Optional[Request]] = [None] * cfg.batch_slots
        self._waiting: List[Request] = []     # kept sorted by arrival
        self._live: Dict[str, Request] = {}   # request_id -> live request
        self._lock = threading.RLock()
        self._arrival_seq = itertools.count()
        self._req_seq = itertools.count()
        # Stats.
        self._tokens_emitted = 0
        self._finished = 0
        self._failed = 0
        self._preemptions = 0
        self._recomputed_tokens = 0
        self._started_at: Optional[float] = None
        self._rate_window: List[tuple] = []   # (t, n) recent emissions
        self._shapes = {"prefill": set(), "decode": set(),
                        "draft_prefill": set(), "propose": set(),
                        "verify": set()}
        # Spec-decode accounting: accepted-length histogram [0..k] per
        # verify round (index a = rounds that accepted exactly a drafts).
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_hist = [0] * (self._draft_len + 1)
        self._build_programs()
        self._last_stats = self._stats_locked()

    # ----------------------------------------------------------- programs

    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import Llama

        model = self._model

        if self._adapters is not None:
            # Multiplexed variants: the adapter banks + per-row index
            # ride as ARGUMENTS (fixed shape/dtype/sharding), so N
            # adapters still mean exactly these two programs — same
            # count as the single-model engine, proven by the compile
            # counters in the multiplex tests and bench_zoo.
            def prefill_fn(params, arenas, banks, aidx, ids, bt, pos,
                           wmask, last_idx):
                logits, arenas = model.apply(
                    params, ids, arenas, bt, pos, wmask, banks, aidx,
                    method=Llama.decode_paged)
                nxt = jnp.argmax(jnp.take_along_axis(
                    logits, last_idx[:, None, None], axis=1)[:, 0],
                    axis=-1)
                return nxt.astype(jnp.int32), arenas

            def decode_fn(params, arenas, banks, aidx, toks, bt, pos,
                          wmask):
                logits, arenas = model.apply(
                    params, toks, arenas, bt, pos, wmask, banks, aidx,
                    method=Llama.decode_paged)
                return jnp.argmax(logits[:, -1],
                                  axis=-1).astype(jnp.int32), arenas
        else:
            def prefill_fn(params, arenas, ids, bt, pos, wmask, last_idx):
                logits, arenas = model.apply(params, ids, arenas, bt, pos,
                                             wmask,
                                             method=Llama.decode_paged)
                nxt = jnp.argmax(jnp.take_along_axis(
                    logits, last_idx[:, None, None], axis=1)[:, 0],
                    axis=-1)
                return nxt.astype(jnp.int32), arenas

            def decode_fn(params, arenas, toks, bt, pos, wmask):
                logits, arenas = model.apply(params, toks, arenas, bt, pos,
                                             wmask,
                                             method=Llama.decode_paged)
                return jnp.argmax(logits[:, -1],
                                  axis=-1).astype(jnp.int32), arenas

        if self.config.use_jit:
            # Arenas are donated: the update is in place on the device,
            # not a fresh copy of the whole cache per step.
            self._prefill_fn = jax.jit(prefill_fn, donate_argnums=(1,))
            self._decode_fn = jax.jit(decode_fn, donate_argnums=(1,))
        else:
            self._prefill_fn = prefill_fn
            self._decode_fn = decode_fn

        # Speculative decoding adds exactly three more fixed-shape
        # programs, each compiled once: draft prefill [1, chunk] (keeps
        # the draft's KV in lockstep with the target's), propose (k+1
        # draft decode steps under lax.scan, [B, 1] per step), verify
        # (target forward over [B, k+1] = current token + k proposals).
        self._draft_prefill_fn = None
        self._propose_fn = None
        self._verify_fn = None
        if self._draft_len > 0:
            draft = self._draft_model

            def draft_prefill_fn(dparams, darenas, ids, bt, pos, wmask):
                _, darenas = draft.apply(dparams, ids, darenas, bt, pos,
                                         wmask, method=Llama.decode_paged)
                return darenas

            def propose_fn(dparams, darenas, toks, bt, pos, wmask_seq):
                # wmask_seq [k+1, B, 1]: per-step write masks (rows near
                # their context limit mask the tail — masked writes land
                # in the trash block, their logits are never used).
                # Step j writes its INPUT token's KV at pos+j and emits
                # the argmax proposal for position pos+j+1, so the k+1
                # steps leave the draft KV complete through pos+k.
                def body(carry, wm):
                    tok, p, arenas = carry
                    logits, arenas = draft.apply(
                        dparams, tok, arenas, bt, p, wm,
                        method=Llama.decode_paged)
                    nxt = jnp.argmax(logits[:, -1],
                                     axis=-1).astype(jnp.int32)
                    return (nxt[:, None], p + 1, arenas), nxt

                (_, _, darenas), props = jax.lax.scan(
                    body, (toks, pos, darenas), wmask_seq)
                return jnp.transpose(props), darenas     # [B, k+1]

            if self._adapters is not None:
                def verify_fn(params, arenas, banks, aidx, toks, bt, pos,
                              wmask):
                    logits, arenas = model.apply(
                        params, toks, arenas, bt, pos, wmask, banks, aidx,
                        method=Llama.decode_paged)
                    return jnp.argmax(logits,
                                      axis=-1).astype(jnp.int32), arenas
            else:
                def verify_fn(params, arenas, toks, bt, pos, wmask):
                    logits, arenas = model.apply(
                        params, toks, arenas, bt, pos, wmask,
                        method=Llama.decode_paged)
                    return jnp.argmax(logits,
                                      axis=-1).astype(jnp.int32), arenas

            if self.config.use_jit:
                self._draft_prefill_fn = jax.jit(draft_prefill_fn,
                                                 donate_argnums=(1,))
                self._propose_fn = jax.jit(propose_fn, donate_argnums=(1,))
                self._verify_fn = jax.jit(verify_fn, donate_argnums=(1,))
            else:
                self._draft_prefill_fn = draft_prefill_fn
                self._propose_fn = propose_fn
                self._verify_fn = verify_fn

    def _program_compiles(self, name: str) -> int:
        fn = {"prefill": self._prefill_fn, "decode": self._decode_fn,
              "draft_prefill": self._draft_prefill_fn,
              "propose": self._propose_fn,
              "verify": self._verify_fn}[name]
        if fn is None:
            return 0
        size = getattr(fn, "_cache_size", None)
        if callable(size):
            try:
                return int(size())
            except Exception:  # noqa: BLE001 — introspection only
                pass
        return len(self._shapes[name])

    # ---------------------------------------------------------- submission

    def register_adapter_source(self, fn: Callable[[str], list]) -> None:
        """Install the on-demand adapter loader: fn(model_id) returns
        the per-layer (aq, bq, ao, bo) rows (api.py wires the replica's
        registered adapter specs here)."""
        self._adapter_source = fn

    def adapter_stats(self) -> Optional[Dict[str, Any]]:
        if self._adapters is None:
            return None
        with self._lock:
            return self._adapters.stats()

    def _resolve_adapter_locked(self, model_id: Optional[str]) -> int:
        if model_id is None:
            return 0
        if self._adapters is None:
            raise ValueError(
                f"request names model {model_id!r} but the engine is not "
                "multiplexed (max_adapters=0)")
        if self._adapter_source is None:
            raise ValueError("no adapter source registered")
        # Rows of live requests are pinned: LRU must never evict weights
        # a mid-flight (or queued) generation still routes through.
        pinned = {r.adapter_row for r in self._live.values()
                  if r.adapter_row}
        return self._adapters.ensure(model_id, self._adapter_source,
                                     pinned_rows=pinned)

    def add_request(self, prompt: List[int],
                    max_new_tokens: int = 16,
                    on_token: Optional[Callable] = None,
                    on_finish: Optional[Callable] = None,
                    request_id: Optional[str] = None,
                    model_id: Optional[str] = None,
                    slo_class: Optional[str] = None) -> Request:
        cfg = self.config
        prompt = [int(t) for t in prompt] or [0]
        max_new_tokens = max(1, int(max_new_tokens))
        slo = slo_class if slo_class is not None else self._slo_default
        if slo not in ("interactive", "batch"):
            raise ValueError(f"unknown slo_class {slo!r} "
                             "(expected 'interactive' or 'batch')")
        total = len(prompt) + max_new_tokens
        if total > cfg.max_context or not self._bm.fits(total):
            raise ValueError(
                f"request needs {total} token slots; engine caps at "
                f"{min(cfg.max_context, self._bm.capacity * cfg.block_size)}"
                f" (max_blocks_per_seq={cfg.max_blocks_per_seq}, "
                f"num_blocks={cfg.num_blocks})")
        with self._lock:
            rid = request_id or f"req-{next(self._req_seq)}"
            if rid in self._live:
                # Reject NOW: a duplicate reaching _admit would raise out
                # of step() and trip the circuit breaker for everyone.
                raise ValueError(f"request id {rid!r} is already live")
            # Adapter residency resolves at submit (load-on-miss, LRU
            # evict): a failure rejects THIS request instead of raising
            # out of step() for everyone.
            adapter_row = self._resolve_adapter_locked(model_id)
            req = Request(
                request_id=rid,
                prompt=prompt, max_new_tokens=max_new_tokens,
                arrival=next(self._arrival_seq),
                on_token=on_token, on_finish=on_finish,
                submitted_at=time.monotonic(),
                trace_ctx=_tracing.capture(),
                model_id=model_id, adapter_row=adapter_row,
                slo_class=slo)
            self._live[rid] = req
            # Queue order is (class, arrival): interactive ahead of
            # batch, FIFO within a class.
            self._waiting.append(req)
            self._waiting.sort(key=self._prio)
            if self._started_at is None:
                self._started_at = time.monotonic()
        return req

    def cancel(self, request_id: str) -> bool:
        """Abort one request (client disconnected mid-stream): free its
        slot and blocks immediately so live traffic isn't stuck behind a
        generation nobody is reading. True if it was still live."""
        emissions: List[tuple] = []
        with self._lock:
            req = self._live.get(request_id)
            if req is None or req.done or req.state == _DONE_HOLD:
                return False   # gone, or already complete (static hold)
            if req.state == WAITING:
                self._waiting.remove(req)
            self._finish(req, emissions, error="cancelled")
        for fn, args in emissions:
            try:
                fn(*args)
            except Exception:  # noqa: BLE001
                pass
        return True

    def has_work(self) -> bool:
        with self._lock:
            # Any occupied slot is work: static DONE_HOLD members still
            # need their gang-release step.
            return bool(self._waiting) or any(
                r is not None for r in self._slots)

    # ---------------------------------------------------------------- step

    def step(self) -> bool:
        """One scheduler iteration: admit, one prefill chunk, one decode
        step. Returns whether any work ran. Callbacks fire after the lock
        is released (they may hop into an asyncio loop)."""
        emissions: List[tuple] = []
        with self._lock:
            self._release_static_gang(emissions)
            self._admit()
            ran = self._prefill_step(emissions)
            if self._draft_len > 0:
                ran = self._spec_decode_step(emissions) or ran
            else:
                ran = self._decode_step(emissions) or ran
        for fn, args in emissions:
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — user callback must not
                pass           # take down the scheduler
        return ran

    def run_until_idle(self, max_steps: int = 10000) -> int:
        """Drive the loop synchronously (tests / offline batch); returns
        steps taken."""
        steps = 0
        while self.has_work():
            if steps >= max_steps:
                raise RuntimeError(f"engine not idle after {max_steps} steps")
            self.step()
            steps += 1
        return steps

    # ----------------------------------------------------------- admission

    def _scheduled(self) -> List[Request]:
        return [r for r in self._slots if r is not None]

    @staticmethod
    def _prio(req: Request):
        return (0 if req.slo_class == "interactive" else 1, req.arrival)

    def _unpin_req(self, req: Request) -> None:
        if req._pinned_node is not None and self._prefix is not None:
            self._prefix.unpin(req._pinned_node)
        req._pinned_node = None

    def _admit(self):
        cfg = self.config
        if cfg.scheduling == "static":
            # Gang admission: only into an EMPTY batch, all at once.
            if any(r is not None for r in self._slots):
                return
        while self._waiting:
            free_slots = [i for i, r in enumerate(self._slots) if r is None]
            if not free_slots:
                return
            req = None
            for cand in self._waiting:   # sorted by (class, arrival)
                if (cfg.scheduling == "continuous"
                        and cand.slo_class != "interactive"
                        and len(free_slots) <= self._slo_reserved):
                    # Reserved headroom: batch-class admissions must
                    # leave this many slots open for interactive
                    # arrivals (a bulk flood otherwise owns the batch).
                    continue
                req = cand
                break
            if req is None:
                return
            rid = req.request_id
            # Longest cached prefix: adopt matched blocks (refcount++)
            # and skip their prefill entirely. Capped one token short of
            # the stream so at least one token still prefills — the
            # first emitted token needs fresh logits.
            matched_tokens = 0
            pin_node = None
            if self._prefix is not None:
                stream = req.prompt + req.generated
                cap = (len(stream) - 1) // cfg.block_size * cfg.block_size
                blocks, pin_node = self._prefix.match(stream[:cap])
                if blocks:
                    matched_tokens = len(blocks) * cfg.block_size
                    self._bm.register_with_blocks(rid, blocks)
                    self._prefix.pin(pin_node)
                    req._pinned_node = pin_node
            if not self._bm.registered(rid):
                self._bm.register(rid)
            first = min(req.total_to_prefill,
                        matched_tokens + cfg.prefill_chunk)
            while not self._bm.ensure(rid, first):
                deficit = (self._bm.blocks_for_tokens(first)
                           - len(self._bm.block_table(rid))
                           - self._bm.num_free())
                if (self._prefix is None
                        or self._prefix.evict_for(deficit) == 0):
                    # Pool exhausted: stay queued; running sequences
                    # finishing (or preempting) will free blocks.
                    self._unpin_req(req)
                    self._bm.free(rid)
                    return
            self._waiting.remove(req)
            req.slot = free_slots[0]
            req.state = PREFILL
            req.processed = matched_tokens
            req.cached_tokens += matched_tokens
            if req.admitted_at is None:
                req.admitted_at = time.monotonic()
            if req.generated:
                self._recomputed_tokens += max(
                    0, req.total_to_prefill - matched_tokens)
            self._slots[req.slot] = req

    # ---------------------------------------------------------- preemption

    def _preempt_one(self) -> bool:
        """Free the lowest-priority scheduled sequence to relieve block
        pressure: batch-class victims before interactive ones, latest
        arrival within a class. The victim may be the requester itself
        (callers detect that via its WAITING state). Returns False when
        there is nothing left to preempt."""
        victims = [r for r in self._scheduled()
                   if r.state in (PREFILL, DECODE)]
        if not victims:
            return False
        victim = max(victims, key=self._prio)
        self._unpin_req(victim)
        self._bm.free(victim.request_id)
        self._slots[victim.slot] = None
        victim.slot = None
        victim.state = WAITING
        victim.processed = 0
        victim.cur_token = None
        victim.preemptions += 1
        self._preemptions += 1
        if _tracing._ENABLED:
            now = _tracing.epoch_of(time.monotonic())
            _tracing.get_tracer().record_span(
                "engine.preempt", now, now, parent_ctx=victim.trace_ctx,
                attrs={"request": victim.request_id,
                       "tokens_generated": len(victim.generated)})
        self._waiting.append(victim)
        self._waiting.sort(key=self._prio)
        return True

    def _ensure_blocks(self, req: Request, num_tokens: int) -> bool:
        """Grow req's block table — reclaiming cold cached prefixes
        first, then preempting victims — until it fits. False when req
        itself was preempted (caller must drop it)."""
        while not self._bm.ensure(req.request_id, num_tokens):
            deficit = (self._bm.blocks_for_tokens(num_tokens)
                       - len(self._bm.block_table(req.request_id))
                       - self._bm.num_free())
            if (self._prefix is not None
                    and self._prefix.evict_for(deficit) > 0):
                continue
            if self.config.scheduling == "static":
                # A drained gang member's KV is never read again — reclaim
                # its blocks before preempting anything still running.
                holders = [r for r in self._scheduled()
                           if r.state == _DONE_HOLD
                           and self._bm.registered(r.request_id)]
                if holders:
                    self._bm.free(holders[0].request_id)
                    continue
            if not self._preempt_one():
                return False
            if req.state == WAITING:   # preempted itself
                return False
        return True

    # ------------------------------------------------------------- prefill

    def _prefill_step(self, emissions) -> bool:
        import numpy as np

        cfg = self.config
        cands = [r for r in self._scheduled() if r.state == PREFILL]
        if not cands:
            return False
        req = min(cands, key=self._prio)   # interactive first, then oldest
        total = req.total_to_prefill
        chunk = min(cfg.prefill_chunk, total - req.processed)
        if not self._ensure_blocks(req, req.processed + chunk):
            return False
        stream = req.prompt + req.generated
        ids = np.zeros((1, cfg.prefill_chunk), np.int32)
        ids[0, :chunk] = stream[req.processed:req.processed + chunk]
        wmask = np.zeros((1, cfg.prefill_chunk), bool)
        wmask[0, :chunk] = True
        bt = self._block_table_rows([req])
        args = (ids, bt, np.asarray([req.processed], np.int32), wmask,
                np.asarray([chunk - 1], np.int32))
        if self._adapters is not None:
            aidx = np.asarray([req.adapter_row], np.int32)
            nxt, self._arenas = self._call(
                "prefill", self._prefill_fn, self._params, self._arenas,
                self._adapters.device_banks(), aidx, *args)
        else:
            nxt, self._arenas = self._call(
                "prefill", self._prefill_fn, self._params, self._arenas,
                *args)
        if self._draft_len > 0:
            # Keep the draft's KV in lockstep: same chunk, same blocks.
            # Cached-prefix blocks carry draft KV from their original
            # prefill (deterministic writes), so hits skip BOTH models.
            self._draft_arenas = self._call(
                "draft_prefill", self._draft_prefill_fn,
                self._draft_params, self._draft_arenas, *args[:4])
        req.processed += chunk
        if req.processed >= total:
            self._emit_token(req, int(nxt[0]), emissions)
        return True

    # -------------------------------------------------------------- decode

    def _decode_step(self, emissions) -> bool:
        import numpy as np

        cfg = self.config
        active: List[Request] = []
        for req in list(self._scheduled()):
            if req.state != DECODE:
                continue
            # Writing cur_token at position `processed` needs capacity for
            # processed + 1 tokens.
            if self._ensure_blocks(req, req.processed + 1):
                active.append(req)
        # A later sequence's block claim may have preempted one already
        # admitted to this step — keep only the still-scheduled.
        active = [r for r in active if r.state == DECODE
                  and r.slot is not None]
        if not active:
            return False
        B = cfg.batch_slots
        toks = np.zeros((B, 1), np.int32)
        pos = np.zeros(B, np.int32)
        wmask = np.zeros((B, 1), bool)
        rows = [None] * B
        for req in active:
            i = req.slot
            rows[i] = req
            toks[i, 0] = req.cur_token
            pos[i] = req.processed
            wmask[i, 0] = True
        bt = self._block_table_rows(rows)
        if self._adapters is not None:
            aidx = np.zeros(B, np.int32)
            for req in active:
                aidx[req.slot] = req.adapter_row
            nxt, self._arenas = self._call(
                "decode", self._decode_fn, self._params, self._arenas,
                self._adapters.device_banks(), aidx, toks, bt, pos, wmask)
        else:
            nxt, self._arenas = self._call(
                "decode", self._decode_fn, self._params, self._arenas,
                toks, bt, pos, wmask)
        nxt = np.asarray(nxt)
        for req in active:
            req.processed += 1
            self._emit_token(req, int(nxt[req.slot]), emissions)
        return True

    def _spec_decode_step(self, emissions) -> bool:
        """Speculative round for every DECODE row: draft proposes k
        tokens (k+1 scan steps so the draft KV stays complete), target
        verifies [current, d1..dk] in one [B, k+1] forward. Row i with
        a accepted drafts emits d1..da plus the target's bonus token —
        provably the same tokens plain decoding would emit (greedy
        verify), just more of them per target pass. Rejected proposals
        need no KV rollback: every stale slot is at a position >= the
        row's new `processed`, and the next round's scatter overwrites
        it before any attention read (the causal mask hides it until
        then). Over-provisioned tail blocks stay in the row's table for
        the next round and are released at finish/preemption — never
        leaked."""
        import numpy as np

        cfg = self.config
        k = self._draft_len
        active: List[tuple] = []
        for req in list(self._scheduled()):
            if req.state != DECODE:
                continue
            # Rows near the context limit shorten their round: writes
            # never pass max_context (the block table has no slots
            # there; a clipped write would corrupt the last block).
            allow = max(0, min(k, cfg.max_context - req.processed - 1))
            if self._ensure_blocks(req, req.processed + allow + 1):
                active.append((req, allow))
        active = [(r, a) for r, a in active
                  if r.state == DECODE and r.slot is not None]
        if not active:
            return False
        B = cfg.batch_slots
        toks = np.zeros((B, 1), np.int32)
        pos = np.zeros(B, np.int32)
        wmask_seq = np.zeros((k + 1, B, 1), bool)
        rows: List[Optional[Request]] = [None] * B
        for req, allow in active:
            i = req.slot
            rows[i] = req
            toks[i, 0] = req.cur_token
            pos[i] = req.processed
            wmask_seq[:allow + 1, i, 0] = True
        bt = self._block_table_rows(rows)
        props, self._draft_arenas = self._call(
            "propose", self._propose_fn, self._draft_params,
            self._draft_arenas, toks, bt, pos, wmask_seq)
        props = np.asarray(props)               # [B, k+1]; col j = d_{j+1}
        vtoks = np.zeros((B, k + 1), np.int32)
        vmask = np.zeros((B, k + 1), bool)
        for req, allow in active:
            i = req.slot
            vtoks[i, 0] = req.cur_token
            vtoks[i, 1:] = props[i, :k]
            vmask[i, :allow + 1] = True
        if self._adapters is not None:
            aidx = np.zeros(B, np.int32)
            for req, _ in active:
                aidx[req.slot] = req.adapter_row
            tgt, self._arenas = self._call(
                "verify", self._verify_fn, self._params, self._arenas,
                self._adapters.device_banks(), aidx, vtoks, bt, pos, vmask)
        else:
            tgt, self._arenas = self._call(
                "verify", self._verify_fn, self._params, self._arenas,
                vtoks, bt, pos, vmask)
        tgt = np.asarray(tgt)                   # [B, k+1] target argmaxes
        for req, allow in active:
            i = req.slot
            a = 0
            while a < allow and props[i, a] == tgt[i, a]:
                a += 1
            self._spec_rounds += 1
            self._spec_proposed += allow
            self._spec_accepted += a
            self._spec_hist[a] += 1
            # KV through pos+a is now final; positions beyond hold
            # rejected-draft garbage the next round overwrites.
            req.processed += a + 1
            for j in range(a + 1):
                if req.done:
                    break
                token = int(props[i, j]) if j < a else int(tgt[i, a])
                self._emit_token(req, token, emissions)
        return True

    # ------------------------------------------------------------- helpers

    def _call(self, name: str, fn, *args):
        self._shapes[name].add(tuple(
            getattr(a, "shape", None) for a in args[2:]))
        return fn(*args)

    def _block_table_rows(self, reqs) -> "np.ndarray":  # noqa: F821
        import numpy as np

        cfg = self.config
        bt = np.zeros((len(reqs), cfg.max_blocks_per_seq), np.int32)
        for i, req in enumerate(reqs):
            if req is None or req.done or req.state == WAITING:
                continue
            table = self._bm.block_table(req.request_id)
            bt[i, :len(table)] = table
        return bt

    def _emit_token(self, req: Request, token: int, emissions):
        req.generated.append(token)
        req.cur_token = token
        req.state = DECODE
        self._record_emit(req, ("token", token), emissions)
        if (len(req.generated) >= req.max_new_tokens
                or (self.config.eos_id is not None
                    and token == self.config.eos_id)):
            self._finish(req, emissions)

    def _record_emit(self, req: Request, event, emissions):
        """Route one client-visible event. Static mode holds everything
        back until the gang drains — that IS the baseline's latency."""
        if self.config.scheduling == "static" and event[0] == "token":
            req._held_emits.append(event)
            return
        self._fire(req, event, emissions)

    def _fire(self, req: Request, event, emissions):
        kind, payload = event
        if kind == "token":
            now = time.monotonic()
            if req.first_token_at is None:
                req.first_token_at = now
            self._tokens_emitted += 1
            self._rate_window.append((now, 1))
            # Prune the stale head here, not just in stats(): an unpolled
            # engine must not grow a tuple per token forever.
            while self._rate_window and now - self._rate_window[0][0] > 5.0:
                self._rate_window.pop(0)
            if req.on_token is not None:
                emissions.append((req.on_token, (req, payload)))
        else:  # finish
            req.finished_at = time.monotonic()
            if req.on_finish is not None:
                emissions.append((req.on_finish, (req,)))

    def _finish(self, req: Request, emissions, error: Optional[str] = None):
        req.state = FAILED if error else FINISHED
        req.error = error
        if error:
            self._failed += 1
        else:
            self._finished += 1
        if self.config.scheduling == "static" and not error:
            # Hold the slot (and blocks) until the whole gang drains:
            # request-level batching runs at the longest member's speed.
            req.state = _DONE_HOLD
            return
        for event in req._held_emits:   # static error: flush, then fail
            self._fire(req, event, emissions)
        req._held_emits = []
        # Donate the finished sequence's full-block prefix to the radix
        # cache BEFORE freeing: insert increfs the novel suffix, free
        # decrefs the request's own references, net the cache keeps
        # exactly the new blocks. Errors skip the donation (a cancelled
        # stream's KV is valid but its tail may be mid-write).
        if (self._prefix is not None and not error
                and self._bm.registered(req.request_id)):
            stream = req.prompt + req.generated
            nb = min(req.processed, len(stream)) // self.config.block_size
            if nb > 0:
                self._prefix.insert(
                    stream[:nb * self.config.block_size],
                    self._bm.block_table(req.request_id)[:nb])
        self._unpin_req(req)
        self._bm.free(req.request_id)
        if req.slot is not None:
            self._slots[req.slot] = None
            req.slot = None
        self._live.pop(req.request_id, None)
        self._fire(req, ("finish", None), emissions)
        self._record_phase_spans(req)

    def fail_all(self, error: str) -> int:
        """Abort every scheduled and waiting request with `error` (the
        EngineLoop's circuit breaker after repeated step failures —
        callers must see the failure, not hang on futures nothing will
        resolve). Completed static gang members are released as
        successes. Returns how many requests were failed."""
        emissions: List[tuple] = []
        failed = 0
        with self._lock:
            for req in list(self._scheduled()):
                if req.state == _DONE_HOLD:
                    self._release_hold(req, emissions)
                else:
                    self._finish(req, emissions, error=error)
                    failed += 1
            for req in self._waiting:
                req.state = FAILED
                req.error = error
                self._failed += 1
                failed += 1
                self._live.pop(req.request_id, None)
                self._fire(req, ("finish", None), emissions)
            self._waiting.clear()
            # Rebuild the arena: a step that failed mid-execution consumed
            # the DONATED buffers without producing replacements, so the
            # old self._arenas may reference deleted arrays — without this
            # every future request would fail on 'Array has been deleted'
            # and the circuit breaker could never actually recover.
            from ray_tpu.models.llama import make_paged_arena

            self._arenas = make_paged_arena(
                self._model.config, self.config.num_blocks,
                self.config.block_size, sharding=self._arena_sharding)
            if self._draft_arenas is not None:
                self._draft_arenas = make_paged_arena(
                    self._draft_model.config, self.config.num_blocks,
                    self.config.block_size,
                    sharding=self._draft_arena_sharding)
            # Fresh arenas invalidate every cached block's contents: a
            # warm radix tree pointing at zeroed KV would serve garbage.
            if self._prefix is not None:
                self._prefix.clear()
        for fn, args in emissions:
            try:
                fn(*args)
            except Exception:  # noqa: BLE001
                pass
        return failed

    def _release_static_gang(self, emissions):
        if self.config.scheduling != "static":
            return
        scheduled = self._scheduled()
        if not scheduled or any(r.state != _DONE_HOLD for r in scheduled):
            return
        for req in scheduled:
            self._release_hold(req, emissions)

    def _release_hold(self, req: Request, emissions):
        """Complete a static DONE_HOLD member: flush its held events in
        order, free its slot and blocks, fire its finish."""
        req.state = FINISHED
        self._live.pop(req.request_id, None)
        for event in req._held_emits:
            self._fire(req, event, emissions)
        req._held_emits = []
        self._bm.free(req.request_id)
        self._slots[req.slot] = None
        req.slot = None
        self._fire(req, ("finish", None), emissions)
        self._record_phase_spans(req)

    def _record_phase_spans(self, req: Request):
        """TTFT decomposition, recorded once per finished request under
        its captured trace context: engine.queue (submit -> first
        admission), engine.prefill (admission -> first token),
        engine.decode (first token -> finish). With engine.preempt
        markers in between, a timeline answers "where did this request's
        latency go" per phase."""
        if not _tracing._ENABLED or req.trace_ctx is None:
            return
        tracer = _tracing.get_tracer()
        eo = _tracing.epoch_of
        end = req.finished_at if req.finished_at is not None \
            else time.monotonic()
        attrs = {"request": req.request_id}
        tracer.record_span(
            "engine.queue", eo(req.submitted_at),
            eo(req.admitted_at if req.admitted_at is not None else end),
            parent_ctx=req.trace_ctx, attrs=attrs, error=req.error)
        if req.admitted_at is not None:
            tracer.record_span(
                "engine.prefill", eo(req.admitted_at),
                eo(req.first_token_at if req.first_token_at is not None
                   else end),
                parent_ctx=req.trace_ctx,
                attrs=dict(attrs, prompt_tokens=len(req.prompt)))
        if req.first_token_at is not None:
            tracer.record_span(
                "engine.decode", eo(req.first_token_at), eo(end),
                parent_ctx=req.trace_ctx,
                attrs=dict(attrs, tokens=len(req.generated),
                           preemptions=req.preemptions))

    # --------------------------------------------------------------- stats

    def stats(self) -> Dict[str, Any]:
        """Engine statistics. Non-blocking: a step mid-XLA-compile can
        hold the engine lock for seconds, and the replica's health check
        (stats with a 1s timeout) must not read that as a dead replica —
        fall back to the last snapshot instead of parking."""
        if not self._lock.acquire(timeout=0.2):
            return dict(self._last_stats)
        try:
            self._last_stats = self._stats_locked()
            return dict(self._last_stats)
        finally:
            self._lock.release()

    def _stats_locked(self) -> Dict[str, Any]:
        now = time.monotonic()
        self._rate_window = [(t, n) for t, n in self._rate_window
                             if now - t <= 5.0]
        window_tokens = sum(n for _, n in self._rate_window)
        span = (now - self._rate_window[0][0]) if self._rate_window else 0.0
        running = [r for r in self._slots if r is not None
                   and r.state in (PREFILL, DECODE)]
        return {
            "queue_depth": len(self._waiting),
            "running": len(running),
            "tp": self._tp,
            "batch_slots": self.config.batch_slots,
            "tokens_emitted": self._tokens_emitted,
            "tokens_per_sec": (window_tokens / span) if span > 0 else 0.0,
            "requests_finished": self._finished,
            "requests_failed": self._failed,
            "preemptions": self._preemptions,
            "recomputed_tokens": self._recomputed_tokens,
            "prefill_compiles": self._program_compiles("prefill"),
            "decode_compiles": self._program_compiles("decode"),
            "kv": self._bm.stats(),
            "prefix_cache": (self._prefix.stats() if self._prefix is not None
                             else {"enabled": False, "cached_blocks": 0,
                                   "hit_rate": 0.0, "hit_tokens": 0}),
            "spec_decode": {
                "draft_len": self._draft_len,
                "rounds": self._spec_rounds,
                "proposed": self._spec_proposed,
                "accepted": self._spec_accepted,
                "accept_rate": (self._spec_accepted / self._spec_proposed
                                if self._spec_proposed else 0.0),
                "mean_accepted": (self._spec_accepted / self._spec_rounds
                                  if self._spec_rounds else 0.0),
                "accepted_hist": list(self._spec_hist),
                "draft_prefill_compiles":
                    self._program_compiles("draft_prefill"),
                "propose_compiles": self._program_compiles("propose"),
                "verify_compiles": self._program_compiles("verify"),
            },
            "slo": {
                "reserved_slots": self._slo_reserved,
                "waiting_interactive": sum(
                    1 for r in self._waiting
                    if r.slo_class == "interactive"),
                "waiting_batch": sum(1 for r in self._waiting
                                     if r.slo_class == "batch"),
            },
            **({"adapters": self._adapters.stats()}
               if self._adapters is not None else {}),
        }

    def check_no_leaks(self):
        """Test hook: once every request has finished, the only arena
        references left are the radix cache's (its synthetic tables are
        audited by check_consistency like live sequences), nothing is
        pinned, and the cache's own tree matches its tables. Without a
        cache this degenerates to the classic blocks_in_use == 0."""
        with self._lock:
            self._bm.check_consistency()
            cached = (self._prefix.cached_blocks()
                      if self._prefix is not None else 0)
            assert self._bm.blocks_in_use() == cached, (
                self._bm.stats(), cached)
            if self._prefix is not None:
                self._prefix.check_consistency()
                if not self._live:
                    assert self._prefix.total_pins() == 0

    def drop_prefix_cache(self) -> int:
        """Release every cached prefix block back to the pool (test
        drains, memory-pressure escape hatch). Returns blocks freed."""
        with self._lock:
            if self._prefix is None:
                return 0
            return self._prefix.clear()


class EngineLoop:
    """Background thread driving `engine.step()` while there is work.

    Submissions from any thread; the replica's asyncio loop talks to it
    through thread-safe callbacks (`api.py`)."""

    def __init__(self, engine: InferenceEngine):
        self.engine = engine
        self._cv = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(target=self._run,
                                        name="inference-engine",
                                        daemon=True)
        self._thread.start()

    # After this many consecutive step failures every in-flight request
    # is failed (fail_all) instead of retrying the same broken state
    # forever while callers hang on futures nothing will resolve.
    MAX_CONSECUTIVE_FAILURES = 3

    def submit(self, *args, **kwargs) -> Request:
        # Check-and-enqueue under the loop's condition: a submit racing
        # stop() must either raise or land before stop's fail_all sweep —
        # never slip into a queue no thread will ever drain.
        with self._cv:
            if self._stopped:
                raise RuntimeError(
                    "engine loop is stopped (replica shutdown)")
            req = self.engine.add_request(*args, **kwargs)
            self._cv.notify()
        return req

    def _run(self):
        failures = 0
        while True:
            with self._cv:
                while not self._stopped and not self.engine.has_work():
                    self._cv.wait(timeout=0.05)
                if self._stopped:
                    return
            try:
                self.engine.step()
                failures = 0
            except Exception as e:  # noqa: BLE001 — scheduler survives a
                failures += 1       # bad step; circuit-break if persistent
                logger.exception("inference engine step failed (%d/%d)",
                                 failures, self.MAX_CONSECUTIVE_FAILURES)
                if failures >= self.MAX_CONSECUTIVE_FAILURES:
                    self.engine.fail_all(
                        f"engine step failed repeatedly: "
                        f"{type(e).__name__}: {e}")
                    failures = 0
                else:
                    time.sleep(0.01)

    def stop(self, timeout_s: float = 5.0):
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._thread.join(timeout=timeout_s)
        # Anything still parked (a request that slipped in as we stopped)
        # must fail fast, not hang its caller.
        self.engine.fail_all("engine loop stopped")
