"""Paged KV-cache block manager (vLLM/PagedAttention-shaped).

The cache arena is a preallocated pool of fixed-size blocks shared by every
sequence (`models/llama.py:make_paged_arena` holds the actual K/V tensors);
this module owns the bookkeeping: which physical blocks belong to which
sequence, in logical order, with refcounts so a fork shares its parent's
blocks copy-on-write. The manager never touches device memory — it hands
out indices, and the engine's jitted step functions read/write the arena
through per-row block tables.

Physical block 0 is reserved as the trash block: the model's scatter sends
masked-off writes (batch padding, prefill-chunk padding) there, so it must
never be allocated to a sequence.

Invariants (asserted by tests):
- a block is free XOR referenced; refcounts are exact across fork/free;
- `blocks_in_use == 0` once every sequence is freed (no leaks);
- allocation never raises on exhaustion — it returns False and the engine
  degrades (preempts a victim) instead of OOMing.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

TRASH_BLOCK = 0


class BlockManager:
    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: deque = deque(range(1, num_blocks))
        self._ref: Dict[int, int] = {}            # physical block -> refcount
        self._tables: Dict[str, List[int]] = {}   # seq id -> logical order
        self._peak_in_use = 0

    # ------------------------------------------------------------- queries

    @property
    def capacity(self) -> int:
        """Allocatable blocks (total minus the trash block)."""
        return self.num_blocks - 1

    def num_free(self) -> int:
        return len(self._free)

    def blocks_in_use(self) -> int:
        return self.capacity - len(self._free)

    def peak_in_use(self) -> int:
        return self._peak_in_use

    def num_seqs(self) -> int:
        return len(self._tables)

    def blocks_for_tokens(self, num_tokens: int) -> int:
        return max(0, -(-num_tokens // self.block_size))

    def fits(self, num_tokens: int) -> bool:
        """Whether a sequence of num_tokens can EVER be resident (engine
        rejects oversized requests at submit time instead of preempting
        forever)."""
        return self.blocks_for_tokens(num_tokens) <= self.capacity

    def block_table(self, seq_id: str) -> List[int]:
        return list(self._tables[seq_id])

    def registered(self, seq_id: str) -> bool:
        return seq_id in self._tables

    # ---------------------------------------------------------- lifecycle

    def register(self, seq_id: str) -> None:
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already registered")
        self._tables[seq_id] = []

    def register_with_blocks(self, seq_id: str, blocks: List[int]) -> None:
        """Register seq_id with an incref'd copy of `blocks` (all must be
        live) — how a radix-cache hit adopts a cached prefix and how cache
        nodes themselves hold their segments. The adopter shares the
        blocks read-only; appends past them land in fresh blocks, so no
        copy-on-write is ever needed on the shared span."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already registered")
        for blk in blocks:
            if blk not in self._ref:
                raise ValueError(f"block {blk} is not live")
        for blk in blocks:
            self._ref[blk] += 1
        self._tables[seq_id] = list(blocks)

    def ensure(self, seq_id: str, num_tokens: int) -> bool:
        """Grow seq_id's table to cover num_tokens. False (and no change)
        when the pool can't supply the missing blocks — caller preempts."""
        table = self._tables[seq_id]
        need = self.blocks_for_tokens(num_tokens) - len(table)
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        for _ in range(need):
            blk = self._free.popleft()
            self._ref[blk] = 1
            table.append(blk)
        self._peak_in_use = max(self._peak_in_use, self.blocks_in_use())
        return True

    def free(self, seq_id: str) -> int:
        """Release a sequence: decref every block, return how many went
        back to the pool (shared blocks stay with the other holder)."""
        table = self._tables.pop(seq_id, None)
        if table is None:
            return 0
        released = 0
        for blk in table:
            self._ref[blk] -= 1
            if self._ref[blk] == 0:
                del self._ref[blk]
                self._free.append(blk)
                released += 1
        return released

    def fork(self, parent_id: str, child_id: str) -> None:
        """Child shares the parent's blocks (refcount++, no copies) —
        beam/parallel sampling shape. Appends by either party must go
        through ensure_appendable first (copy-on-write)."""
        if child_id in self._tables:
            raise ValueError(f"sequence {child_id!r} already registered")
        table = self._tables[parent_id]
        for blk in table:
            self._ref[blk] += 1
        self._tables[child_id] = list(table)

    def ensure_appendable(self, seq_id: str
                          ) -> Optional[Tuple[int, int]]:
        """Copy-on-write for the last block: if it is shared (refcount >
        1), claim a fresh block in its place and return (src, dst) so the
        caller copies the arena contents; None when nothing to do. Returns
        (src, -1) without changes when the pool is exhausted — caller
        preempts and retries."""
        table = self._tables[seq_id]
        if not table:
            return None
        last = table[-1]
        if self._ref[last] == 1:
            return None
        if not self._free:
            return (last, -1)
        dst = self._free.popleft()
        self._ref[dst] = 1
        self._ref[last] -= 1
        table[-1] = dst
        self._peak_in_use = max(self._peak_in_use, self.blocks_in_use())
        return (last, dst)

    def check_consistency(self) -> None:
        """Every block is free XOR referenced, refcounts match the tables
        (test hook; cheap enough to run after every scenario)."""
        counts: Dict[int, int] = {}
        for table in self._tables.values():
            for blk in table:
                counts[blk] = counts.get(blk, 0) + 1
        assert counts == self._ref, (counts, self._ref)
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate free blocks"
        assert not (free & set(self._ref)), "block both free and referenced"
        assert TRASH_BLOCK not in free and TRASH_BLOCK not in self._ref
        assert len(free) + len(self._ref) == self.capacity

    def stats(self) -> Dict[str, int]:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "blocks_in_use": self.blocks_in_use(),
            "blocks_free": self.num_free(),
            "peak_blocks_in_use": self._peak_in_use,
            "sequences": self.num_seqs(),
        }


# --------------------------------------------------------------------------- #
# Radix prefix cache: shared-prefix KV reuse at block granularity
# --------------------------------------------------------------------------- #


class _RadixNode:
    """One edge of the radix tree. `key` is a tuple of block-symbols
    (each symbol = one full block's token ids), `blocks` the physical
    blocks holding that segment's KV, `seq_id` the synthetic BlockManager
    table that owns the cache's refcounts on them."""

    __slots__ = ("key", "blocks", "seq_id", "children", "parent",
                 "last_used", "pins")

    def __init__(self, key, blocks, parent):
        self.key = key                  # tuple of block-symbol tuples
        self.blocks = blocks            # list of physical block ids
        self.seq_id: Optional[str] = None
        self.children: Dict[tuple, "_RadixNode"] = {}
        self.parent: Optional["_RadixNode"] = parent
        self.last_used = 0
        self.pins = 0


class RadixPrefixCache:
    """Radix tree over token-id paths mapping shared prefixes to
    refcounted block-table segments (the vLLM automatic-prefix-caching
    shape, at block granularity).

    The alphabet is FULL BLOCKS: a symbol is the tuple of `block_size`
    token ids that fill one block, so a match is always block-aligned and
    a matched block's KV can be adopted verbatim — partial blocks cannot
    be shared (their tail would need a rewrite) and never enter the tree.

    Ownership: every node registers a synthetic sequence in the
    BlockManager (`~radixN`) holding one reference per cached block, so
    `check_consistency()` audits the cache exactly like live sequences
    and `blocks_in_use == cached_blocks()` is the idle-engine no-leak
    invariant. A hit adopts the matched blocks via
    `register_with_blocks` (refcount++), making eviction safe at any
    moment: freeing a node only drops the CACHE's reference, adopters
    keep theirs.

    Pinning: a live sequence pins the deepest node of its matched path;
    eviction only ever removes unpinned LEAF nodes (LRU by a
    deterministic logical clock), so a pinned node's ancestors are
    structurally protected without their own pins.

    The cache stores bookkeeping only — device KV stays in the arena; on
    an arena rebuild (`engine.fail_all`) the tree must be `clear()`ed
    because every cached block's contents are gone."""

    def __init__(self, bm: BlockManager):
        self._bm = bm
        self._root = _RadixNode((), [], None)
        self._clock = itertools.count(1)
        self._ids = itertools.count()
        self._cached_blocks = 0
        # Counters (exported via stats()).
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0

    # ------------------------------------------------------------- helpers

    def _symbols(self, tokens: List[int]) -> List[tuple]:
        bs = self._bm.block_size
        return [tuple(tokens[i * bs:(i + 1) * bs])
                for i in range(len(tokens) // bs)]

    def _new_node(self, key, blocks, parent) -> _RadixNode:
        node = _RadixNode(tuple(key), list(blocks), parent)
        node.seq_id = f"~radix{next(self._ids)}"
        self._bm.register_with_blocks(node.seq_id, node.blocks)
        node.last_used = next(self._clock)
        parent.children[node.key[0]] = node
        self._cached_blocks += len(node.blocks)
        return node

    def _split(self, child: _RadixNode, m: int) -> _RadixNode:
        """Split `child` after its first m symbols; returns the new top
        node (covering exactly the matched part). The original node
        object keeps its pins/children and becomes the bottom part. New
        tables register BEFORE the old one frees, so no refcount ever
        touches zero mid-split."""
        assert 0 < m < len(child.key)
        parent = child.parent
        top = _RadixNode(child.key[:m], child.blocks[:m], parent)
        top.seq_id = f"~radix{next(self._ids)}"
        self._bm.register_with_blocks(top.seq_id, top.blocks)
        bottom_id = f"~radix{next(self._ids)}"
        self._bm.register_with_blocks(bottom_id, child.blocks[m:])
        self._bm.free(child.seq_id)   # top+bottom hold refs: releases 0
        parent.children[top.key[0]] = top
        child.key = child.key[m:]
        child.blocks = child.blocks[m:]
        child.seq_id = bottom_id
        child.parent = top
        top.children = {child.key[0]: child}
        top.last_used = next(self._clock)
        return top

    def _nodes(self) -> List[_RadixNode]:
        out, stack = [], [self._root]
        while stack:
            n = stack.pop()
            if n is not self._root:
                out.append(n)
            stack.extend(n.children.values())
        return out

    # ----------------------------------------------------------- interface

    def match(self, tokens: List[int]):
        """Longest cached prefix of `tokens` (full blocks only). Returns
        (blocks, deepest_node) — the caller adopts `blocks` via
        `register_with_blocks` and pins `deepest_node` for the life of
        the sequence (None on a miss). Splits mid-edge matches so the
        pinned node covers exactly the matched span."""
        syms = self._symbols(tokens)
        self.lookups += 1
        node, blocks, i = self._root, [], 0
        while i < len(syms):
            child = node.children.get(syms[i])
            if child is None:
                break
            m = 0
            while (m < len(child.key) and i + m < len(syms)
                   and child.key[m] == syms[i + m]):
                m += 1
            if m < len(child.key):
                child = self._split(child, m)
            blocks.extend(child.blocks)
            child.last_used = next(self._clock)
            node = child
            i += len(child.key)
        if node is self._root:
            return [], None
        self.hits += 1
        self.hit_tokens += len(blocks) * self._bm.block_size
        return blocks, node

    def pin(self, node: Optional[_RadixNode]) -> None:
        if node is not None:
            node.pins += 1

    def unpin(self, node: Optional[_RadixNode]) -> None:
        if node is not None and node.pins > 0:
            node.pins -= 1

    def insert(self, tokens: List[int], blocks: List[int]) -> int:
        """Record a finished sequence's full-block prefix. Walks existing
        edges (shared spans dedupe onto the tree's blocks — the donor's
        duplicates go back to the pool when it frees) and registers only
        the novel suffix. Returns how many blocks the cache newly
        references."""
        syms = self._symbols(tokens)
        assert len(syms) == len(blocks), (len(syms), len(blocks))
        node, i = self._root, 0
        while i < len(syms):
            child = node.children.get(syms[i])
            if child is None:
                new = self._new_node(syms[i:], blocks[i:], node)
                self.inserted_blocks += len(new.blocks)
                return len(new.blocks)
            m = 0
            while (m < len(child.key) and i + m < len(syms)
                   and child.key[m] == syms[i + m]):
                m += 1
            if m < len(child.key):
                child = self._split(child, m)
            child.last_used = next(self._clock)
            node = child
            i += len(child.key)
        return 0

    def evict_for(self, need_blocks: int) -> int:
        """Free least-recently-used unpinned leaves until `need_blocks`
        pool blocks were actually released (adopters may keep a freed
        node's blocks alive — those count for the cache but not for the
        pool). Returns blocks released to the pool; 0 means nothing was
        evictable."""
        freed = 0
        while freed < need_blocks:
            leaves = [n for n in self._nodes()
                      if not n.children and n.pins == 0]
            if not leaves:
                break
            victim = min(leaves, key=lambda n: n.last_used)
            freed += self._remove(victim)
        return freed

    def _remove(self, node: _RadixNode) -> int:
        released = self._bm.free(node.seq_id)
        del node.parent.children[node.key[0]]
        self._cached_blocks -= len(node.blocks)
        self.evicted_blocks += len(node.blocks)
        node.parent = None
        return released

    def clear(self) -> int:
        """Drop every cached segment (arena rebuild / test drain). Safe
        with live adopters: they hold their own refs and never write the
        shared span. Returns blocks released to the pool."""
        released = 0
        for node in self._nodes():
            released += self._bm.free(node.seq_id)
        self._root.children = {}
        self._cached_blocks = 0
        return released

    def cached_blocks(self) -> int:
        return self._cached_blocks

    def total_pins(self) -> int:
        return sum(n.pins for n in self._nodes())

    def check_consistency(self) -> None:
        """Tree bookkeeping matches the BlockManager's tables exactly."""
        total = 0
        for node in self._nodes():
            assert node.seq_id is not None and node.key, node
            assert len(node.key) == len(node.blocks), node
            assert self._bm.block_table(node.seq_id) == node.blocks
            assert node.parent is not None
            assert node.parent.children.get(node.key[0]) is node
            total += len(node.blocks)
        assert total == self._cached_blocks, (total, self._cached_blocks)

    def stats(self) -> Dict[str, Any]:
        nodes = self._nodes()
        return {
            "enabled": True,
            "nodes": len(nodes),
            "cached_blocks": self._cached_blocks,
            "pinned_nodes": sum(1 for n in nodes if n.pins),
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": (self.hits / self.lookups) if self.lookups else 0.0,
            "hit_tokens": self.hit_tokens,
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
        }
