"""Paged KV-cache block manager (vLLM/PagedAttention-shaped).

The cache arena is a preallocated pool of fixed-size blocks shared by every
sequence (`models/llama.py:make_paged_arena` holds the actual K/V tensors);
this module owns the bookkeeping: which physical blocks belong to which
sequence, in logical order, with refcounts so a fork shares its parent's
blocks copy-on-write. The manager never touches device memory — it hands
out indices, and the engine's jitted step functions read/write the arena
through per-row block tables.

Physical block 0 is reserved as the trash block: the model's scatter sends
masked-off writes (batch padding, prefill-chunk padding) there, so it must
never be allocated to a sequence.

Invariants (asserted by tests):
- a block is free XOR referenced; refcounts are exact across fork/free;
- `blocks_in_use == 0` once every sequence is freed (no leaks);
- allocation never raises on exhaustion — it returns False and the engine
  degrades (preempts a victim) instead of OOMing.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

TRASH_BLOCK = 0


class BlockManager:
    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: deque = deque(range(1, num_blocks))
        self._ref: Dict[int, int] = {}            # physical block -> refcount
        self._tables: Dict[str, List[int]] = {}   # seq id -> logical order
        self._peak_in_use = 0

    # ------------------------------------------------------------- queries

    @property
    def capacity(self) -> int:
        """Allocatable blocks (total minus the trash block)."""
        return self.num_blocks - 1

    def num_free(self) -> int:
        return len(self._free)

    def blocks_in_use(self) -> int:
        return self.capacity - len(self._free)

    def peak_in_use(self) -> int:
        return self._peak_in_use

    def num_seqs(self) -> int:
        return len(self._tables)

    def blocks_for_tokens(self, num_tokens: int) -> int:
        return max(0, -(-num_tokens // self.block_size))

    def fits(self, num_tokens: int) -> bool:
        """Whether a sequence of num_tokens can EVER be resident (engine
        rejects oversized requests at submit time instead of preempting
        forever)."""
        return self.blocks_for_tokens(num_tokens) <= self.capacity

    def block_table(self, seq_id: str) -> List[int]:
        return list(self._tables[seq_id])

    def registered(self, seq_id: str) -> bool:
        return seq_id in self._tables

    # ---------------------------------------------------------- lifecycle

    def register(self, seq_id: str) -> None:
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id!r} already registered")
        self._tables[seq_id] = []

    def ensure(self, seq_id: str, num_tokens: int) -> bool:
        """Grow seq_id's table to cover num_tokens. False (and no change)
        when the pool can't supply the missing blocks — caller preempts."""
        table = self._tables[seq_id]
        need = self.blocks_for_tokens(num_tokens) - len(table)
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        for _ in range(need):
            blk = self._free.popleft()
            self._ref[blk] = 1
            table.append(blk)
        self._peak_in_use = max(self._peak_in_use, self.blocks_in_use())
        return True

    def free(self, seq_id: str) -> int:
        """Release a sequence: decref every block, return how many went
        back to the pool (shared blocks stay with the other holder)."""
        table = self._tables.pop(seq_id, None)
        if table is None:
            return 0
        released = 0
        for blk in table:
            self._ref[blk] -= 1
            if self._ref[blk] == 0:
                del self._ref[blk]
                self._free.append(blk)
                released += 1
        return released

    def fork(self, parent_id: str, child_id: str) -> None:
        """Child shares the parent's blocks (refcount++, no copies) —
        beam/parallel sampling shape. Appends by either party must go
        through ensure_appendable first (copy-on-write)."""
        if child_id in self._tables:
            raise ValueError(f"sequence {child_id!r} already registered")
        table = self._tables[parent_id]
        for blk in table:
            self._ref[blk] += 1
        self._tables[child_id] = list(table)

    def ensure_appendable(self, seq_id: str
                          ) -> Optional[Tuple[int, int]]:
        """Copy-on-write for the last block: if it is shared (refcount >
        1), claim a fresh block in its place and return (src, dst) so the
        caller copies the arena contents; None when nothing to do. Returns
        (src, -1) without changes when the pool is exhausted — caller
        preempts and retries."""
        table = self._tables[seq_id]
        if not table:
            return None
        last = table[-1]
        if self._ref[last] == 1:
            return None
        if not self._free:
            return (last, -1)
        dst = self._free.popleft()
        self._ref[dst] = 1
        self._ref[last] -= 1
        table[-1] = dst
        self._peak_in_use = max(self._peak_in_use, self.blocks_in_use())
        return (last, dst)

    def check_consistency(self) -> None:
        """Every block is free XOR referenced, refcounts match the tables
        (test hook; cheap enough to run after every scenario)."""
        counts: Dict[int, int] = {}
        for table in self._tables.values():
            for blk in table:
                counts[blk] = counts.get(blk, 0) + 1
        assert counts == self._ref, (counts, self._ref)
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate free blocks"
        assert not (free & set(self._ref)), "block both free and referenced"
        assert TRASH_BLOCK not in free and TRASH_BLOCK not in self._ref
        assert len(free) + len(self._ref) == self.capacity

    def stats(self) -> Dict[str, int]:
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "blocks_in_use": self.blocks_in_use(),
            "blocks_free": self.num_free(),
            "peak_blocks_in_use": self._peak_in_use,
            "sequences": self.num_seqs(),
        }
