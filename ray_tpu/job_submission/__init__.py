"""Job submission: run driver scripts against a cluster, track their fate.

Equivalent of the reference's job submission stack (`JobSubmissionClient`,
`dashboard/modules/job/job_manager.py:507`): a job is an entrypoint shell
command spawned near the head node with the cluster address in its
environment; status transitions PENDING -> RUNNING -> SUCCEEDED / FAILED /
STOPPED are tracked server-side and logs are captured per job.

The manager runs inside the GCS process (this framework has no separate
dashboard process); the client talks to it over the normal GCS RPC channel,
so `JobSubmissionClient(address)` works from anywhere that can reach the
cluster.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.core.rpc import RpcClient


class JobStatus:
    # SUBMITTED: accepted into the GCS job table, driver not launched yet
    # (agent path); the legacy in-GCS manager reports PENDING instead.
    SUBMITTED = "SUBMITTED"
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobDetails:
    submission_id: str
    entrypoint: str
    status: str
    message: str = ""
    start_time: Optional[float] = None
    end_time: Optional[float] = None
    metadata: Dict[str, str] = field(default_factory=dict)
    # Agent-path jobs only (jobs/state.py public_details — keep in sync);
    # the legacy manager leaves these at their defaults.
    runtime_env: Dict[str, Any] = field(default_factory=dict)
    tenant: str = ""
    node_id: Optional[str] = None
    driver_job_id: Optional[str] = None


class JobSubmissionClient:
    """Client API (reference `ray.job_submission.JobSubmissionClient`)."""

    def __init__(self, address: str):
        # Accept "ray://host:port", "http://host:port" or bare "host:port" —
        # they all route to the GCS RPC endpoint here.
        for prefix in ("ray://", "http://", "https://"):
            if address.startswith(prefix):
                address = address[len(prefix):]
        self._client = RpcClient(address, name="job-client")

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   tenant: Optional[Any] = None) -> str:
        """Submit an entrypoint. `runtime_env` is prepared CLIENT-side
        (working_dir/py_modules zip + upload to the GCS blob store) so
        the job record only ever carries content-addressed URIs — the
        agent node needs no access to the client's filesystem. `tenant`
        is a tenant name (str) or TenantSpec-shaped dict; the job's
        tasks are then admitted under that tier/weight/rate quota by
        every raylet dispatch loop (docs/JOBS.md "Jobs as tenants")."""
        if runtime_env:
            from ray_tpu.core.runtime_env import prepare

            runtime_env = prepare(runtime_env, self._client)
        resp = self._client.call("submit_job", {
            "entrypoint": entrypoint, "submission_id": submission_id,
            "runtime_env": runtime_env, "metadata": metadata or {},
            "tenant": tenant})
        if resp.get("error"):
            raise RuntimeError(resp["error"])
        return resp["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        return self._details(submission_id).status

    def get_job_info(self, submission_id: str) -> JobDetails:
        return self._details(submission_id)

    def _details(self, submission_id: str) -> JobDetails:
        resp = self._client.call("job_info", {"submission_id": submission_id})
        if resp is None or not resp.get("found"):
            raise ValueError(f"no job with submission_id {submission_id!r}")
        return JobDetails(**resp["details"])

    def get_job_logs(self, submission_id: str) -> str:
        resp = self._client.call("job_logs", {"submission_id": submission_id})
        if not resp.get("found"):
            raise ValueError(f"no job with submission_id {submission_id!r}")
        return resp["logs"]

    def stop_job(self, submission_id: str) -> bool:
        return bool(self._client.call(
            "stop_job", {"submission_id": submission_id}).get("stopped"))

    def delete_job(self, submission_id: str) -> bool:
        return bool(self._client.call(
            "delete_job", {"submission_id": submission_id}).get("deleted"))

    def list_jobs(self) -> List[JobDetails]:
        return [JobDetails(**d) for d in self._client.call("list_jobs")]

    def tail_job_logs(self, submission_id: str, poll_s: float = 0.5):
        """Generator of new log chunks until the job terminates."""
        import time

        seen = 0
        # Unbounded by API contract (tail -f semantics: follow the job
        # until it terminates); the bound is the TERMINAL status check —
        # a dead job server fails the poll's own RPC instead of hanging.
        while True:  # raylint: disable=RL010
            logs = self.get_job_logs(submission_id)
            if len(logs) > seen:
                yield logs[seen:]
                seen = len(logs)
            if self.get_job_status(submission_id) in JobStatus.TERMINAL:
                rest = self.get_job_logs(submission_id)
                if len(rest) > seen:
                    yield rest[seen:]
                return
            time.sleep(poll_s)

    def close(self):
        self._client.close()


__all__ = ["JobStatus", "JobDetails", "JobSubmissionClient"]
