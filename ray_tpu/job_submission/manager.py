"""Server half of job submission: spawn, monitor, and log entrypoints.

Reference: `dashboard/modules/job/job_manager.py:507` (the reference runs
drivers via a JobSupervisor actor; here the GCS process supervises the
subprocess directly — one fewer moving part, same state machine).
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.job_submission import JobStatus


class JobManager:
    def __init__(self, gcs_address: str, log_dir: str):
        self._gcs_address = gcs_address
        self._log_dir = log_dir
        self._lock = threading.Lock()
        self._jobs: Dict[str, Dict[str, Any]] = {}

    # ---------------------------------------------------------------- API

    def submit(self, entrypoint: str, submission_id: Optional[str] = None,
               runtime_env: Optional[Dict[str, Any]] = None,
               metadata: Optional[Dict[str, str]] = None) -> str:
        sid = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        with self._lock:
            if sid in self._jobs:
                raise ValueError(f"submission_id {sid!r} already exists")
            self._jobs[sid] = {
                "entrypoint": entrypoint, "status": JobStatus.PENDING,
                "message": "", "start_time": None, "end_time": None,
                "metadata": metadata or {}, "proc": None,
                "log_path": os.path.join(self._log_dir, f"job-{sid}.log")}
        threading.Thread(target=self._run, args=(sid, runtime_env),
                         name=f"job-{sid[:12]}", daemon=True).start()
        return sid

    def _run(self, sid: str, runtime_env: Optional[Dict[str, Any]]):
        job = self._jobs[sid]
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self._gcs_address
        env["RAY_TPU_SUBMISSION_ID"] = sid
        # The entrypoint must import the SAME framework this cluster runs
        # (which may not be pip-installed, and /tmp/ray_tpu session dirs
        # can shadow the package as a namespace package).
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        pp = env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            env["PYTHONPATH"] = pkg_root + (os.pathsep + pp if pp else "")
        for k, v in ((runtime_env or {}).get("env_vars") or {}).items():
            env[k] = str(v)
        cwd = (runtime_env or {}).get("working_dir") or None
        os.makedirs(self._log_dir, exist_ok=True)
        try:
            with open(job["log_path"], "wb") as logf:
                with self._lock:
                    if job["status"] == JobStatus.STOPPED:
                        # stop_job() won the race before the spawn: honor it.
                        job["end_time"] = time.time()
                        return
                    proc = subprocess.Popen(
                        job["entrypoint"], shell=True, stdout=logf,
                        stderr=subprocess.STDOUT, env=env, cwd=cwd,
                        start_new_session=True)
                    job["proc"] = proc
                    job["status"] = JobStatus.RUNNING
                    job["start_time"] = time.time()
                rc = proc.wait()
            with self._lock:
                job["end_time"] = time.time()
                if job["status"] == JobStatus.STOPPED:
                    pass  # stop_job already labeled it
                elif rc == 0:
                    job["status"] = JobStatus.SUCCEEDED
                else:
                    job["status"] = JobStatus.FAILED
                    job["message"] = f"entrypoint exited with code {rc}"
        except Exception as e:  # noqa: BLE001 — spawn failure
            with self._lock:
                job["status"] = JobStatus.FAILED
                job["message"] = f"failed to start: {e}"
                job["end_time"] = time.time()

    def details(self, sid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            job = self._jobs.get(sid)
            if job is None:
                return None
            return {"submission_id": sid, "entrypoint": job["entrypoint"],
                    "status": job["status"], "message": job["message"],
                    "start_time": job["start_time"],
                    "end_time": job["end_time"],
                    "metadata": dict(job["metadata"])}

    def logs(self, sid: str) -> Optional[str]:
        with self._lock:
            job = self._jobs.get(sid)
        if job is None:
            return None
        try:
            with open(job["log_path"], "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop(self, sid: str) -> bool:
        with self._lock:
            job = self._jobs.get(sid)
            if job is None or job["status"] in JobStatus.TERMINAL:
                return False
            job["status"] = JobStatus.STOPPED
            proc = job["proc"]
        if proc is not None and proc.poll() is None:
            try:
                # The entrypoint may have children (driver spawns workers
                # elsewhere, but shell pipelines are local): kill the group.
                os.killpg(os.getpgid(proc.pid), 15)
            except Exception:  # noqa: BLE001
                try:
                    proc.terminate()
                except Exception:  # noqa: BLE001
                    pass
        return True

    def delete(self, sid: str) -> bool:
        with self._lock:
            job = self._jobs.get(sid)
            if job is None or job["status"] not in JobStatus.TERMINAL:
                return False
            del self._jobs[sid]
        try:
            os.unlink(job["log_path"])
        except OSError:
            pass
        return True

    def list(self) -> List[Dict[str, Any]]:
        with self._lock:
            sids = list(self._jobs)
        return [d for sid in sids if (d := self.details(sid)) is not None]

    def shutdown(self):
        with self._lock:
            sids = [s for s, j in self._jobs.items()
                    if j["status"] == JobStatus.RUNNING]
        for sid in sids:
            self.stop(sid)
