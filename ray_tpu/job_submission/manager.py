"""Server half of job submission: spawn, monitor, and log entrypoints.

Reference: `dashboard/modules/job/job_manager.py:507` (the reference runs
drivers via a JobSupervisor actor; here the GCS process supervises the
subprocess directly — one fewer moving part, same state machine).
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.job_submission import JobStatus
from ray_tpu.jobs import procutil


class JobManager:
    def __init__(self, gcs_address: str, log_dir: str):
        self._gcs_address = gcs_address
        self._log_dir = log_dir
        self._lock = threading.Lock()
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._closed = False

    # ---------------------------------------------------------------- API

    def submit(self, entrypoint: str, submission_id: Optional[str] = None,
               runtime_env: Optional[Dict[str, Any]] = None,
               metadata: Optional[Dict[str, str]] = None) -> str:
        sid = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        runner = threading.Thread(target=self._run, args=(sid, runtime_env),
                                  name=f"job-{sid[:12]}", daemon=True)
        with self._lock:
            if self._closed:
                # The RPC server keeps serving submits during GCS
                # teardown (it stops AFTER job_manager.shutdown()); a
                # job admitted here would spawn after the kill sweep and
                # be orphaned when the process exits.
                raise RuntimeError("job manager is shut down")
            if sid in self._jobs:
                raise ValueError(f"submission_id {sid!r} already exists")
            self._jobs[sid] = {
                "entrypoint": entrypoint, "status": JobStatus.PENDING,
                "message": "", "start_time": None, "end_time": None,
                "metadata": metadata or {}, "proc": None,
                "runner": runner, "killer": None,
                "log_path": os.path.join(self._log_dir, f"job-{sid}.log")}
        runner.start()
        return sid

    # Kill-handshake hygiene lives in jobs/procutil.py now, shared with
    # the per-node job agent; these shims keep the existing call sites
    # (and the direct unit tests against them) stable.
    _kill_group = staticmethod(procutil.kill_group)
    _wait_group_dead = staticmethod(procutil.wait_group_dead)

    def _run(self, sid: str, runtime_env: Optional[Dict[str, Any]]):
        job = self._jobs[sid]
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self._gcs_address
        env["RAY_TPU_SUBMISSION_ID"] = sid
        # The entrypoint must import the SAME framework this cluster runs
        # (which may not be pip-installed, and /tmp/ray_tpu session dirs
        # can shadow the package as a namespace package).
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        pp = env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            env["PYTHONPATH"] = pkg_root + (os.pathsep + pp if pp else "")
        for k, v in ((runtime_env or {}).get("env_vars") or {}).items():
            env[k] = str(v)
        cwd = (runtime_env or {}).get("working_dir") or None
        os.makedirs(self._log_dir, exist_ok=True)
        try:
            with open(job["log_path"], "wb") as logf:
                with self._lock:
                    if job["status"] == JobStatus.STOPPED:
                        # stop() won the race before the spawn: honor it.
                        job["end_time"] = time.time()
                        return
                # Spawn OUTSIDE the lock (raylint RL002): fork/exec can
                # take hundreds of ms and would stall every status query
                # and submit on the shared lock.
                proc = subprocess.Popen(
                    job["entrypoint"], shell=True, stdout=logf,
                    stderr=subprocess.STDOUT, env=env, cwd=cwd,
                    start_new_session=True)
                with self._lock:
                    stopped = job["status"] == JobStatus.STOPPED
                    if not stopped:
                        job["proc"] = proc
                        job["status"] = JobStatus.RUNNING
                        job["start_time"] = time.time()
                if stopped:
                    # stop() raced the spawn and found no proc to kill:
                    # the kill is ours to deliver.
                    self._kill_group(proc)
                    with self._lock:
                        job["end_time"] = time.time()
                    return
                rc = proc.wait()
            with self._lock:
                job["end_time"] = time.time()
                if job["status"] == JobStatus.STOPPED:
                    pass  # stop_job already labeled it
                elif rc == 0:
                    job["status"] = JobStatus.SUCCEEDED
                else:
                    job["status"] = JobStatus.FAILED
                    job["message"] = f"entrypoint exited with code {rc}"
        except Exception as e:  # noqa: BLE001 — spawn failure
            with self._lock:
                job["status"] = JobStatus.FAILED
                job["message"] = f"failed to start: {e}"
                job["end_time"] = time.time()

    def details(self, sid: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            job = self._jobs.get(sid)
            if job is None:
                return None
            return {"submission_id": sid, "entrypoint": job["entrypoint"],
                    "status": job["status"], "message": job["message"],
                    "start_time": job["start_time"],
                    "end_time": job["end_time"],
                    "metadata": dict(job["metadata"])}

    def logs(self, sid: str) -> Optional[str]:
        with self._lock:
            job = self._jobs.get(sid)
        if job is None:
            return None
        try:
            with open(job["log_path"], "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop(self, sid: str) -> bool:
        with self._lock:
            job = self._jobs.get(sid)
            if job is None or job["status"] in JobStatus.TERMINAL:
                return False
            job["status"] = JobStatus.STOPPED
            proc = job["proc"]
            killer = None
            if proc is not None and proc.poll() is None:
                # The entrypoint may have children (driver spawns workers
                # elsewhere, but shell pipelines are local): kill the
                # group, escalating to SIGKILL off-thread so a
                # TERM-trapping driver cannot outlive its STOPPED status
                # — and so this RPC-path caller never blocks on the grace
                # period. Published under the SAME lock hold that flips
                # the status: shutdown()'s waiter snapshot must never see
                # a STOPPED job whose killer is still unrecorded, or the
                # join that proves kill delivery silently skips it.
                # (poll() is WNOHANG — no RL002 concern.)
                killer = threading.Thread(
                    target=self._kill_group, args=(proc,),
                    name=f"job-kill-{sid[:12]}", daemon=True)
                job["killer"] = killer
        if killer is not None:
            killer.start()
        return True

    def delete(self, sid: str) -> bool:
        with self._lock:
            job = self._jobs.get(sid)
            if job is None or job["status"] not in JobStatus.TERMINAL:
                return False
            del self._jobs[sid]
        try:
            os.unlink(job["log_path"])
        except OSError:
            pass
        return True

    def list(self) -> List[Dict[str, Any]]:
        with self._lock:
            sids = list(self._jobs)
        return [d for sid in sids if (d := self.details(sid)) is not None]

    def shutdown(self, timeout_s: float = 10.0):
        # PENDING included: a job whose spawn is still in flight gets
        # marked STOPPED here, and the runner thread's post-spawn
        # handshake (see _run) delivers the kill to the process group it
        # just created — skipping it would orphan the entrypoint.
        with self._lock:
            self._closed = True  # later submits raise instead of orphaning
            sids = [s for s, j in self._jobs.items()
                    if j["status"] in (JobStatus.PENDING, JobStatus.RUNNING)]
        for sid in sids:
            self.stop(sid)
        # The signals are delivered off-thread (stop() must not block its
        # RPC caller on the grace period), but shutdown() is the last
        # exit ramp before the supervising process dies — returning with
        # a daemon killer still in flight would orphan an entrypoint
        # whose SIGTERM never got sent. Join the killer (stop() path) and
        # the runner (PENDING-spawn handshake path + reap) of EVERY job,
        # not just the ones this call stopped: a client stop() moments
        # before shutdown leaves its killer mid-grace too. Joins on
        # finished jobs' dead threads return immediately; the deadline
        # bounds a wedged entrypoint past the SIGKILL escalation.
        deadline = time.monotonic() + timeout_s
        with self._lock:
            waiters = [t for j in self._jobs.values()
                       for t in (j["killer"], j["runner"])
                       if t is not None]
        for t in waiters:
            while True:
                try:
                    t.join(timeout=max(0.0, deadline - time.monotonic()))
                    break
                except RuntimeError:
                    # Published in _jobs but not yet start()ed by its
                    # spawning thread (submit/stop release the lock
                    # before start()); the start is imminent — yield and
                    # retry rather than skip its kill delivery.
                    if time.monotonic() >= deadline:
                        break
                    time.sleep(0.01)
