"""ray_tpu.jobs — the multi-job platform tier.

Sits between the core runtime and clients: submitted jobs live in a
GCS-owned, checkpointed job table; per-node agents (`jobs/agent.py`,
hosted inside each raylet) launch driver subprocesses with kill-handshake
hygiene (`jobs/procutil.py`) and stream logs back; the raylet dispatch
loop applies per-job fairness and rate quotas (`jobs/tenancy.py`) so a
batch job's task storm and serve traffic share one admission model.

Client entry point is `ray_tpu.job_submission.JobSubmissionClient`
(`submit_job(entrypoint, runtime_env=..., tenant=...)`); see
docs/JOBS.md for the submission API, the runtime_env contract,
detached-actor lifetimes, and cleanup guarantees.
"""

from ray_tpu.jobs import procutil  # noqa: F401
from ray_tpu.jobs.agent import JobAgent  # noqa: F401
from ray_tpu.jobs.state import (  # noqa: F401
    FAILED, RUNNING, STOPPED, SUBMITTED, SUCCEEDED, TERMINAL,
    is_terminal, new_record, public_details,
)
from ray_tpu.jobs.tenancy import JobAdmission  # noqa: F401
