"""Per-node job agent: launches driver subprocesses for submitted jobs.

Reference: `dashboard/modules/job/job_manager.py` supervises drivers via
a detached JobSupervisor actor per job; here the agent is a plain object
hosted INSIDE each raylet (registered RPC endpoints `agent_run_job` /
`agent_stop_job`), which gives the same placement property — the driver
runs on a worker node, not inside the GCS — without a separate daemon.

Contract with the GCS (which owns the job table):

- `run_job(sid, entrypoint, runtime_env)` spawns the entrypoint with the
  PR-4 kill-handshake hygiene (`start_new_session=True`, group-liveness
  escalation from jobs/procutil.py) and returns immediately; a runner
  thread then reports `job_started` {sid, pid}, streams stdout/stderr
  lines to `job_log_append` in batched flushes (LogStreamer cadence:
  0.25 s flush tick, bounded batch with a dropped counter — a driver
  print-storm costs bounded RPC traffic, never unbounded memory), and
  finally reports `job_terminal` {sid, returncode, message}.
- `stop_job(sid)` delivers the group kill off-thread (the RPC caller
  never blocks on the SIGTERM grace window).
- `running()` is the reconcile list `register_node` carries after a
  raylet restart: RUNNING jobs the GCS thinks live here but the fresh
  agent doesn't know are marked FAILED instead of hanging forever.

The driver inherits the job's runtime_env two ways: `env_vars` go into
its process environment directly, and the full prepared runtime_env
rides in `RAY_TPU_JOB_RUNTIME_ENV` so the driver-side runtime adopts it
as the default for every task/actor it submits (that's what points the
job's tasks at the right per-env forge template).
"""

from __future__ import annotations

import io
import json
import logging
import os
import subprocess
import threading
import time
import zipfile
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.jobs import procutil

logger = logging.getLogger(__name__)

_FLUSH_INTERVAL_S = 0.25
_FLUSH_MAX_LINES = 500
_BUFFER_CAP_LINES = 2000


class JobAgent:
    """One per raylet. `gcs_call(method, params)` is the raylet's
    reconnecting GCS client — reports survive a GCS restart."""

    def __init__(self, node_id_hex: str, session_dir: str,
                 gcs_call: Callable[[str, Dict[str, Any]], Any],
                 gcs_address: str):
        self._node_id_hex = node_id_hex
        self._session_dir = session_dir
        self._gcs_call = gcs_call
        self._gcs_address = gcs_address
        self._lock = threading.Lock()
        # sid -> {proc, runner, killer, stopped}; entries are removed when
        # the runner reports terminal (job-cleanup handoff: the GCS job
        # table is the durable record, this is live-process state only).
        self._jobs: Dict[str, Dict[str, Any]] = {}
        self._closed = False

    # ---------------------------------------------------------------- API

    def run_job(self, sid: str, entrypoint: str,
                runtime_env: Optional[Dict[str, Any]] = None) -> None:
        runner = threading.Thread(
            target=self._run, args=(sid, entrypoint, runtime_env or {}),
            name=f"job-agent-{sid[:12]}", daemon=True)
        with self._lock:
            if self._closed:
                raise RuntimeError("job agent is shut down")
            if sid in self._jobs:
                raise ValueError(f"job {sid!r} already running on this node")
            self._jobs[sid] = {"proc": None, "runner": runner,
                               "killer": None, "stopped": False}
        runner.start()

    def stop_job(self, sid: str) -> bool:
        with self._lock:
            job = self._jobs.get(sid)
            if job is None:
                return False
            job["stopped"] = True
            proc = job["proc"]
            killer = None
            if proc is not None and proc.poll() is None and \
                    job["killer"] is None:
                # Group kill escalates off-thread (same reasoning as
                # JobManager.stop): the RPC caller must not ride out the
                # grace period, and the killer is published under the
                # SAME lock hold as the stopped flag so shutdown()'s
                # join sweep cannot miss it.
                killer = threading.Thread(
                    target=procutil.kill_group, args=(proc,),
                    name=f"job-agent-kill-{sid[:12]}", daemon=True)
                job["killer"] = killer
        if killer is not None:
            killer.start()
        return True

    def running(self) -> List[str]:
        with self._lock:
            return list(self._jobs)

    def shutdown(self, timeout_s: float = 10.0) -> None:
        with self._lock:
            self._closed = True
            sids = list(self._jobs)
        for sid in sids:
            self.stop_job(sid)
        deadline = time.monotonic() + timeout_s
        with self._lock:
            waiters = [t for j in self._jobs.values()
                       for t in (j["killer"], j["runner"]) if t is not None]
        for t in waiters:
            while True:
                try:
                    t.join(timeout=max(0.0, deadline - time.monotonic()))
                    break
                except RuntimeError:
                    # published but not yet start()ed; the start is
                    # imminent — yield rather than skip kill delivery.
                    if time.monotonic() >= deadline:
                        break
                    time.sleep(0.01)

    # ------------------------------------------------------------- runner

    def _run(self, sid: str, entrypoint: str,
             runtime_env: Dict[str, Any]) -> None:
        job = self._jobs[sid]
        try:
            env, cwd = self._driver_env(sid, runtime_env)
        except Exception as e:  # noqa: BLE001 — env materialization failed
            self._report("job_terminal",
                         {"submission_id": sid, "returncode": -1,
                          "message": f"runtime_env failed: {e}"})
            with self._lock:
                self._jobs.pop(sid, None)
            return
        try:
            with self._lock:
                if job["stopped"]:
                    self._report("job_terminal",
                                 {"submission_id": sid, "returncode": -1,
                                  "message": "stopped before start",
                                  "stopped": True})
                    self._jobs.pop(sid, None)
                    return
            # Spawn OUTSIDE the lock (raylint RL002): fork/exec can take
            # hundreds of ms and would stall stop/run RPCs meanwhile.
            proc = subprocess.Popen(
                entrypoint, shell=True, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, env=env, cwd=cwd,
                start_new_session=True)
        except Exception as e:  # noqa: BLE001 — spawn failure
            self._report("job_terminal",
                         {"submission_id": sid, "returncode": -1,
                          "message": f"failed to start: {e}"})
            with self._lock:
                self._jobs.pop(sid, None)
            return
        with self._lock:
            stopped = job["stopped"]
            if not stopped:
                job["proc"] = proc
        if stopped:
            # stop raced the spawn and found no proc: the kill is ours.
            procutil.kill_group(proc)
            self._report("job_terminal",
                         {"submission_id": sid, "returncode": -1,
                          "message": "stopped", "stopped": True})
            with self._lock:
                self._jobs.pop(sid, None)
            return
        self._report("job_started", {"submission_id": sid, "pid": proc.pid})
        self._pump_logs(sid, proc)
        rc = proc.wait()
        with self._lock:
            was_stopped = job["stopped"]
            killer = job["killer"]
        if killer is not None:
            killer.join(timeout=10.0)
        msg = "" if rc == 0 else f"entrypoint exited with code {rc}"
        self._report("job_terminal",
                     {"submission_id": sid, "returncode": rc,
                      "message": "stopped" if was_stopped else msg,
                      "stopped": was_stopped})
        with self._lock:
            self._jobs.pop(sid, None)

    def _pump_logs(self, sid: str, proc: subprocess.Popen) -> None:
        """Stream the driver's output to the GCS log plane in batched
        flushes. Runs on the runner thread until EOF (process exit)."""
        assert proc.stdout is not None
        buf: List[str] = []
        dropped = 0
        last_flush = time.monotonic()

        def flush():
            nonlocal buf, dropped, last_flush
            if buf or dropped:
                self._report("job_log_append",
                             {"submission_id": sid, "lines": buf,
                              "dropped": dropped})
                buf, dropped = [], 0
            last_flush = time.monotonic()

        for raw in io.TextIOWrapper(proc.stdout, errors="replace"):
            if len(buf) >= _BUFFER_CAP_LINES:
                dropped += 1  # print storm: count, don't buffer unbounded
            else:
                buf.append(raw.rstrip("\n"))
            if len(buf) >= _FLUSH_MAX_LINES or \
                    time.monotonic() - last_flush >= _FLUSH_INTERVAL_S:
                flush()
        flush()

    # ------------------------------------------------------------ plumbing

    def _report(self, method: str, params: Dict[str, Any]) -> None:
        params["node_id"] = self._node_id_hex
        try:
            self._gcs_call(method, params)
        except Exception:  # noqa: BLE001 — GCS down; reconcile will catch up
            logger.warning("job agent: %s report failed", method,
                           exc_info=True)

    def _driver_env(self, sid: str, runtime_env: Dict[str, Any]):
        env = dict(os.environ)
        env["RAY_TPU_ADDRESS"] = self._gcs_address
        env["RAY_TPU_SUBMISSION_ID"] = sid
        # The entrypoint must import the SAME framework this cluster runs
        # (which may not be pip-installed).
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        pp = env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            env["PYTHONPATH"] = pkg_root + (os.pathsep + pp if pp else "")
        for k, v in (runtime_env.get("env_vars") or {}).items():
            env[str(k)] = str(v)
        if runtime_env:
            env["RAY_TPU_JOB_RUNTIME_ENV"] = json.dumps(runtime_env)
        cwd = None
        wd = runtime_env.get("working_dir")
        if wd:
            cwd = self._materialize_working_dir(wd)
            env["RAY_TPU_JOB_CWD"] = cwd
        return env, cwd

    def _materialize_working_dir(self, wd: str) -> str:
        """A prepared working_dir is a `kv://runtime_env/<sha>.zip` URI:
        fetch + extract under the session dir (content-addressed, shared
        with worker-side materialization). A plain directory path passes
        through — single-node convenience."""
        from ray_tpu.core.runtime_env import URI_PREFIX, _KV_NS

        if not wd.startswith(URI_PREFIX):
            if not os.path.isdir(wd):
                raise ValueError(f"working_dir {wd!r} is not a directory")
            return os.path.abspath(wd)
        sha = wd[len(URI_PREFIX):-len(".zip")]
        cache = os.path.join(self._session_dir, "runtime_env")
        dest = os.path.join(cache, sha)
        if os.path.isdir(dest):
            return dest
        os.makedirs(cache, exist_ok=True)
        resp = self._gcs_call("kv_get", {"namespace": _KV_NS,
                                         "key": wd.encode()})
        blob = resp.get("value")
        if blob is None:
            raise RuntimeError(f"runtime_env blob {wd} missing from GCS KV")
        import shutil
        import tempfile

        tmp = tempfile.mkdtemp(prefix=f"{sha}.", dir=cache)
        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            if not os.path.isdir(dest):
                raise
            shutil.rmtree(tmp, ignore_errors=True)  # lost the race
        return dest
