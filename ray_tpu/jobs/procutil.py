"""Process-group kill hygiene shared by every entrypoint supervisor.

Factored out of `job_submission/manager.py` (the PR-4 kill handshake) so
the per-node job agent and the legacy in-GCS JobManager escalate
identically: SIGTERM the group, wait out a grace window keyed on GROUP
liveness (not the direct child's), then SIGKILL survivors and confirm
the group is gone before returning.
"""

from __future__ import annotations

import os
import subprocess
import time


def kill_group(proc: subprocess.Popen, grace_s: float = 3.0) -> None:
    """SIGTERM the entrypoint's process group, then SIGKILL whatever
    part of it outlives grace_s: a TERM-trapping driver must not
    survive shutdown or park the waiting runner thread forever.

    The direct child is the `sh -c` wrapper (shell=True), and its
    death says nothing about the group — the shell dies on TERM
    while a TERM-trapping python driver it spawned survives in the
    same group. So the escalation is keyed on GROUP liveness, probed
    with killpg(pgid, 0): while any member lives the pgid (== the
    leader's pid, via start_new_session=True) cannot be recycled, so
    a positive probe means the KILL lands on our group, never on a
    stranger whose group reused a freed pid. The probe and the
    signal cannot be fully atomic — the residual window is the
    microseconds between them, within which the whole pid space
    would have to wrap for the signal to land elsewhere."""
    def _sig(sig, fallback):
        try:
            os.killpg(proc.pid, sig)
        except OSError:
            try:
                fallback()
            except OSError:
                pass  # exited and reaped in between
    _sig(15, proc.terminate)
    if not wait_group_dead(proc, grace_s):
        _sig(9, proc.kill)
        # Confirm the group is actually gone before returning: callers
        # join the killing thread as their proof of kill delivery, and
        # one that exits the process the moment we return must not race
        # the SIGKILLed survivors' death. Bounded — SIGKILL cannot be
        # trapped, so this only waits out the kernel teardown and
        # init's zombie reap.
        wait_group_dead(proc, 2.0)
    try:
        proc.wait(timeout=2.0)
    except subprocess.TimeoutExpired:
        pass  # stuck in uninterruptible sleep past SIGKILL; stay bounded


def wait_group_dead(proc: subprocess.Popen, timeout_s: float) -> bool:
    """Poll until no member of the entrypoint's process group remains
    (killpg(pgid, 0) -> ESRCH), reaping the direct child along the
    way. False if the group still has members after timeout_s."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            os.killpg(proc.pid, 0)
        except OSError:
            return True  # whole group exited (and was reaped)
        if time.monotonic() >= deadline:
            return False
        if proc.returncode is None:
            try:
                proc.wait(timeout=0.1)  # reap the shell + pace the poll
            except subprocess.TimeoutExpired:
                pass
        else:
            time.sleep(0.05)  # child reaped; poll surviving group
