"""Submitted-job records: the schema of the GCS job table.

One dict per submission, created here so the GCS (which persists and
mutates records), the job agent (which reports transitions), and the
client (which reads `public_details`) agree on the fields. States follow
the reference's submission state machine
(`dashboard/modules/job/common.py:JobStatus`) minus PENDING-vs-SUBMITTED
hairsplitting: a record is SUBMITTED until its driver process is alive.

    SUBMITTED --> RUNNING --> SUCCEEDED | FAILED
        \\------------------> STOPPED    (client stop, node death rules)

Terminal states never transition again — a late agent report against a
STOPPED/deleted record is dropped, not resurrected.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

SUBMITTED = "SUBMITTED"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"

TERMINAL = (SUCCEEDED, FAILED, STOPPED)


def new_record(sid: str, entrypoint: str,
               runtime_env: Optional[Dict[str, Any]],
               metadata: Optional[Dict[str, str]],
               tenant_qos: Optional[Dict[str, Any]],
               env_sig: str, now: float) -> Dict[str, Any]:
    return {
        "submission_id": sid,
        "entrypoint": entrypoint,
        "state": SUBMITTED,
        "message": "",
        "runtime_env": dict(runtime_env or {}),
        "env_sig": env_sig,
        "metadata": dict(metadata or {}),
        "tenant_qos": dict(tenant_qos or {}),
        "submit_time": now,
        "start_time": None,
        "end_time": None,
        # Where the agent runs the driver (node hex) and what it reported.
        "node_id": None,
        "driver_pid": None,
        # Driver JobID hex, linked when the entrypoint calls ray_tpu.init()
        # and register_job carries RAY_TPU_SUBMISSION_ID back to us.
        "driver_job_id": None,
    }


def public_details(rec: Dict[str, Any]) -> Dict[str, Any]:
    """The wire form JobSubmissionClient builds JobDetails from — keep in
    sync with `job_submission.JobDetails` (dataclass ctor takes **this)."""
    return {
        "submission_id": rec["submission_id"],
        "entrypoint": rec["entrypoint"],
        "status": rec["state"],
        "message": rec["message"],
        "start_time": rec["start_time"],
        "end_time": rec["end_time"],
        "metadata": dict(rec["metadata"]),
        "runtime_env": dict(rec["runtime_env"]),
        "tenant": rec["tenant_qos"].get("name", ""),
        "node_id": rec["node_id"],
        "driver_job_id": rec["driver_job_id"],
    }


def is_terminal(rec: Dict[str, Any]) -> bool:
    return rec["state"] in TERMINAL
