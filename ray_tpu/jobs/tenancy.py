"""Jobs-as-tenants: per-job admission for the raylet dispatch loop.

The serve plane enforces tenant quotas at the proxy (async WFQ +
token bucket in `tenancy/admission.py`); batch jobs never pass a proxy —
their task storms land straight in the raylet queue. This module is the
dispatch-loop counterpart: synchronous, called with the raylet's queue
lock held, so it must stay O(1) per decision with no blocking.

- **Stride scheduling** replaces virtual-time WFQ (same fairness
  guarantee, simpler without an event loop): each job carries a `pass`
  value advanced by `1/weight` per dispatched task; the dispatcher
  offers the next slot to the backlogged job with the LOWEST pass, so a
  weight-8 (gold) job gets ~8 dispatches for every one a weight-1
  (bronze) job gets, and an idle job re-enters at the current global
  pass (no banked credit, no starvation).
- **Token bucket** (`rps_limit`/`burst` from the job's TenantSpec) caps
  a job's dispatch RATE outright; a throttled job's tasks stay queued
  and the 0.2 s dispatch tick retries — tasks are never rejected, only
  delayed (unlike the proxy's fast 429, a queued task has nowhere to
  bounce back to).

Jobs register from the GCS JOB-channel "running" event (tenant QoS rides
along) and unregister on "finished" — including interactive drivers that
never went through submit_job (every driver job publishes both events),
so entries cannot outlive their job.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional

from ray_tpu.tenancy.admission import TokenBucket
from ray_tpu.tenancy.registry import TIER_WEIGHTS


class _JobEntry:
    __slots__ = ("weight", "bucket", "pass_value", "name")

    def __init__(self, weight: float, bucket: Optional[TokenBucket],
                 pass_value: float, name: str):
        self.weight = max(1.0, float(weight))
        self.bucket = bucket
        self.pass_value = pass_value
        self.name = name


class JobAdmission:
    """Per-job dispatch admission keyed by driver JobID hex.

    All methods are called from the raylet dispatch thread (under the
    queue lock) plus the GCS-push thread for register/unregister — the
    touched state is plain dict/float ops, safe under the GIL for this
    read-mostly pattern; the dispatch loop re-checks feasibility anyway.
    """

    def __init__(self, default_weight: float = 4.0):
        self._default_weight = max(1.0, float(default_weight))
        # job hex -> entry; bounded by live jobs: unregister() runs on
        # every job's "finished" event (GCS publishes it for submitted
        # AND interactive drivers alike).
        self._jobs: Dict[str, _JobEntry] = {}
        self._global_pass = 0.0

    # ------------------------------------------------------------ lifecycle

    def register(self, job_hex: str, qos: Optional[Dict[str, Any]]) -> None:
        qos = qos or {}
        weight = qos.get("weight") or TIER_WEIGHTS.get(
            qos.get("tier", ""), self._default_weight)
        rps = float(qos.get("rps_limit") or 0.0)
        bucket = TokenBucket(rps, float(qos.get("burst") or rps)) \
            if rps > 0 else None
        entry = self._jobs.get(job_hex)
        if entry is None:
            self._jobs[job_hex] = _JobEntry(
                weight, bucket, self._global_pass, qos.get("name", ""))
        else:  # quota update: rebuild rate state, keep the stride pass
            entry.weight = max(1.0, float(weight))
            entry.bucket = bucket
            entry.name = qos.get("name", "")

    def unregister(self, job_hex: str) -> None:
        self._jobs.pop(job_hex, None)

    def _entry(self, job_hex: str) -> _JobEntry:
        entry = self._jobs.get(job_hex)
        if entry is None:
            # Interactive driver the push hasn't announced (or raced):
            # default weight, unmetered. Its "finished" event still
            # reaches unregister(), so lazy entries are reclaimed too.
            entry = self._jobs[job_hex] = _JobEntry(
                self._default_weight, None, self._global_pass, "")
        return entry

    # ------------------------------------------------------------ dispatch

    def order(self, job_hexes: Iterable[str]) -> List[str]:
        """Backlogged jobs in stride order (lowest pass first — the job
        the fair schedule owes the next dispatch slot)."""
        return sorted(set(job_hexes),
                      key=lambda h: self._entry(h).pass_value)

    def admit(self, job_hex: str, now: Optional[float] = None) -> float:
        """Charge one dispatch to the job. 0.0 = admitted (token taken,
        stride pass advanced); > 0 = throttled for that many seconds
        (nothing consumed — the task stays queued)."""
        entry = self._entry(job_hex)
        if entry.bucket is not None:
            wait = entry.bucket.take(
                time.monotonic() if now is None else now)
            if wait > 0.0:
                return wait
        entry.pass_value += 1.0 / entry.weight
        self._global_pass = max(self._global_pass, entry.pass_value)
        return 0.0

    def refund(self, job_hex: str) -> None:
        """Undo an admit whose dispatch could not complete (resource
        acquire lost a race): give the token and the stride turn back so
        the failed attempt doesn't count against the job's share."""
        entry = self._jobs.get(job_hex)
        if entry is None:
            return
        entry.pass_value = max(0.0, entry.pass_value - 1.0 / entry.weight)
        if entry.bucket is not None:
            entry.bucket._tokens = min(entry.bucket.burst,
                                       entry.bucket._tokens + 1.0)

    # ------------------------------------------------------------ introspect

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        return {h: {"weight": e.weight, "pass": round(e.pass_value, 4),
                    "tenant": e.name, "metered": e.bucket is not None}
                for h, e in self._jobs.items()}
