"""Model zoo: flax models with logical-axis sharding annotations."""

from ray_tpu.models.gpt2 import GPT2, GPT2Config
from ray_tpu.models.llama import Llama, LlamaConfig
from ray_tpu.models.mlp import MLP
from ray_tpu.models.moe import MoE, MoEConfig

__all__ = ["GPT2", "GPT2Config", "Llama", "LlamaConfig", "MLP",
           "MoE", "MoEConfig"]
