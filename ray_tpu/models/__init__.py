"""Model zoo: flax models with logical-axis sharding annotations."""

from ray_tpu.models.gpt2 import GPT2, GPT2Config
from ray_tpu.models.mlp import MLP

__all__ = ["GPT2", "GPT2Config", "MLP"]
