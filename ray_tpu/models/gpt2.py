"""GPT-2 in flax, sharding-annotated for dp/fsdp/tp/sp meshes.

The flagship model (BASELINE.json: "JaxTrainer — GPT-2-small"). Every
parameter carries logical axes (mapped to mesh axes by
`ray_tpu.parallel.sharding.DEFAULT_RULES`): embeddings shard vocab over tp
and embed over fsdp; attention/MLP matmuls are Megatron-style column-then-row
parallel over tp so each block needs one psum on tp; activations are
constrained to ("batch", "seq", ...) so dp/fsdp shard the batch and sp shards
the sequence (ring attention).

bfloat16 compute, float32 params/optimizer: MXU-friendly without loss-scale
bookkeeping.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304          # padded to a multiple of 128 for the MXU
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_flash: bool = True
    use_ring: bool = False           # sequence parallelism (sp axis)
    remat: bool = False              # jax.checkpoint each block
    flash_block_q: int = 0   # 0 = pick_block_sizes auto heuristic
    flash_block_k: int = 0

    @staticmethod
    def small() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def medium() -> "GPT2Config":
        return GPT2Config(n_embd=1024, n_layer=24, n_head=16)

    @staticmethod
    def tiny(seq: int = 128) -> "GPT2Config":
        return GPT2Config(vocab_size=512, n_positions=seq, n_embd=128,
                          n_layer=2, n_head=4)


def _dense(features: int, logical_axes: Tuple[str, ...], config: GPT2Config,
           name: str, use_bias: bool = True):
    return nn.Dense(
        features,
        use_bias=use_bias,
        dtype=config.dtype,
        param_dtype=config.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(0.02), logical_axes),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros, (logical_axes[-1],)),
        name=name,
    )


class Block(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        cfg = self.config
        head_dim = cfg.n_embd // cfg.n_head
        h = nn.LayerNorm(dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="ln_1")(x)
        # Column-parallel QKV (tp shards heads), row-parallel output proj.
        qkv = _dense(3 * cfg.n_embd, ("embed", "mlp"), cfg, "c_attn")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        b, s, _ = q.shape

        def heads(t):
            t = t.reshape(b, s, cfg.n_head, head_dim)
            t = nn.with_logical_constraint(t, ("batch", "seq", "heads", None))
            return t.transpose(0, 2, 1, 3)  # [b, heads, seq, d]

        q, k, v = heads(q), heads(k), heads(v)
        if cfg.use_ring:
            from ray_tpu.ops.ring_attention import ring_attention

            attn = ring_attention(q, k, v, axis_name="sp", causal=True)
        elif cfg.use_flash:
            attn = flash_attention(q, k, v, True, None,
                                   cfg.flash_block_q, cfg.flash_block_k)
        else:
            from ray_tpu.ops.attention import mha_reference

            attn = mha_reference(q, k, v, causal=True)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_embd)
        attn = _dense(cfg.n_embd, ("mlp", "embed"), cfg, "c_proj")(attn)
        if cfg.dropout:
            attn = nn.Dropout(cfg.dropout)(attn, deterministic=deterministic)
        x = x + attn
        h2 = nn.LayerNorm(dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                          name="ln_2")(x)
        h2 = _dense(4 * cfg.n_embd, ("embed", "mlp"), cfg, "c_fc")(h2)
        h2 = nn.gelu(h2)
        h2 = _dense(cfg.n_embd, ("mlp", "embed"), cfg, "mlp_proj")(h2)
        if cfg.dropout:
            h2 = nn.Dropout(cfg.dropout)(h2, deterministic=deterministic)
        x = x + h2
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class GPT2(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids, deterministic: bool = True):
        cfg = self.config
        b, s = input_ids.shape
        wte = self.param(
            "wte",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         ("vocab", "embed")),
            (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        wpe = self.param(
            "wpe",
            nn.with_logical_partitioning(nn.initializers.normal(0.01),
                                         (None, "embed")),
            (cfg.n_positions, cfg.n_embd), cfg.param_dtype)
        x = wte.astype(cfg.dtype)[input_ids] + wpe.astype(cfg.dtype)[None, :s]
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=(2,))
        for i in range(cfg.n_layer):
            x = block(cfg, name=f"h_{i}")(x, deterministic)
        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="ln_f")(x)
        # Tied output head: logits over the sharded vocab.
        logits = jnp.einsum("bse,ve->bsv", x, wte.astype(cfg.dtype))
        return nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))


# --------------------------------------------------------------------------- #
# Sharded init / loss / train-step factory
# --------------------------------------------------------------------------- #


def logical_param_specs(model: nn.Module, sample_shape: Tuple[int, int]):
    """Abstract-eval the model and return the logical PartitionSpec pytree."""
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros(sample_shape, jnp.int32)))
    return nn.get_partition_spec(abstract)


def mesh_shardings_for(model: nn.Module, mesh,
                       sample_shape: Tuple[int, int],
                       rules: Optional[Dict[str, Any]] = None):
    """NamedSharding pytree for the model params on `mesh`."""
    from ray_tpu.parallel.sharding import logical_axis_rules

    logical = logical_param_specs(model, sample_shape)
    rule_list = logical_axis_rules(rules, mesh_axes=mesh.axis_names)
    with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") \
            else _null():
        resolved = nn.logical_to_mesh_sharding(logical, mesh, rule_list)
    return resolved


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def init_sharded(model: nn.Module, mesh, sample_shape: Tuple[int, int],
                 seed: int = 0):
    """Initialize parameters directly into their mesh shardings (no host
    round-trip: init is jitted with out_shardings)."""
    shardings = mesh_shardings_for(model, mesh, sample_shape)

    def init_fn():
        return model.init(jax.random.PRNGKey(seed),
                          jnp.zeros(sample_shape, jnp.int32))

    return jax.jit(init_fn, out_shardings=shardings)()


def next_token_loss(logits, targets, ignore_index: int = -100):
    """Shifted cross-entropy in float32.

    nll = logsumexp(logits) - logits[target] rather than log_softmax +
    gather: identical math, but XLA only materializes the [b, s] reduce
    and gather instead of a normalized [b, s, vocab] float32 tensor —
    measured ~4% step-time win on v5e (the vocab dim dominates HBM
    traffic for small models)."""
    logits = logits[:, :-1].astype(jnp.float32)
    targets = targets[:, 1:]
    mask = targets != ignore_index
    targets = jnp.where(mask, targets, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = lse - tgt
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)


def make_train_step(model: nn.Module, optimizer, mesh=None,
                    donate: bool = True, loss_fn=None):
    """Jitted (params, opt_state, batch) -> (params, opt_state, loss).

    With a mesh: logical axis rules resolve the with_logical_constraint
    annotations; data enters sharded ("batch" over dp+fsdp, "seq" over sp);
    XLA places the psums over tp/sp on ICI.

    `loss_fn(params, batch) -> (objective, displayed_loss)` customizes the
    training objective (MoE adds router losses to the cross-entropy); the
    default is next-token cross-entropy for both.
    """
    from flax.linen import logical_axis_rules as flax_rules

    from ray_tpu.parallel.sharding import logical_axis_rules

    rules = logical_axis_rules(
        mesh_axes=mesh.axis_names if mesh is not None else None)

    if loss_fn is None:
        def loss_fn(p, batch):
            logits = model.apply(p, batch["input_ids"])
            ce = next_token_loss(logits, batch["labels"])
            return ce, ce

    def step(params, opt_state, batch):
        (_, shown), grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch), has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, shown

    def step_with_rules(params, opt_state, batch):
        with flax_rules(rules):
            return step(params, opt_state, batch)

    donate_argnums = (0, 1) if donate else ()
    if mesh is not None:
        with mesh:
            return jax.jit(step_with_rules, donate_argnums=donate_argnums)
    return jax.jit(step_with_rules, donate_argnums=donate_argnums)


def make_eval_step(model: nn.Module):
    @jax.jit
    def eval_step(params, batch):
        logits = model.apply(params, batch["input_ids"])
        return next_token_loss(logits, batch["labels"])

    return eval_step


def count_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


def flops_per_token(cfg: GPT2Config, seq_len: int) -> float:
    """Approximate training FLOPs per token (6N + attention)."""
    n = (12 * cfg.n_layer * cfg.n_embd ** 2
         + cfg.vocab_size * cfg.n_embd)
    attn = 12 * cfg.n_layer * cfg.n_embd * seq_len
    return 6.0 * n + 2.0 * attn
