"""Llama-family decoder in flax, sharding-annotated, with KV-cache decode.

Second model family (BASELINE.json names a Llama Serve deployment next to
the GPT-2 trainer): RMSNorm, rotary position embeddings, SwiGLU MLP,
grouped-query attention, untied LM head — the same logical-axis annotations
as `gpt2.py` (tp shards heads/mlp, dp/fsdp shard batch, sp shards seq), so
`make_train_step`/`mesh_shardings_for` work unchanged.

Three forward paths share parameters:
- `__call__(input_ids)` — full-sequence training forward (flash attention).
- `decode(input_ids, cache, pos)` — incremental inference against a
  preallocated KV cache: prefill writes the prompt's K/V once, each decode
  step attends a 1-token query over the cache (O(context) memory reads
  instead of an O(context^2) recompute per token).
- `decode_paged(input_ids, arenas, block_tables, pos, write_mask)` — the
  same incremental math against a PAGED cache (vLLM/PagedAttention shape):
  K/V live in a shared fixed-size block arena; each row's block table maps
  logical blocks to physical ones, so the continuous-batching engine
  (`ray_tpu/inference/`) can admit/evict/preempt sequences without ever
  reshaping the cache — one compiled program per (batch, step-width)
  shape, forever.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import flash_attention, mha_reference


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000          # 250 * 128: already MXU-aligned
    n_positions: int = 4096
    n_embd: int = 4096
    n_layer: int = 32
    n_head: int = 32
    n_kv_head: int = 8               # grouped-query attention
    intermediate: int = 11008        # SwiGLU hidden width
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_flash: bool = True
    remat: bool = False
    # Sequence parallelism: a mesh with an "sp" axis routes the training
    # forward's attention through the ring (ops/ring_attention) — each
    # device holds a sequence shard, K/V rotate over ppermute. None (or
    # a mesh without "sp") keeps the flash/reference path.
    sp_mesh: Any = None

    @staticmethod
    def llama7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def small() -> "LlamaConfig":
        """~110M-param config for single-chip experiments."""
        return LlamaConfig(n_embd=768, n_layer=12, n_head=12, n_kv_head=4,
                           intermediate=2048, n_positions=2048)

    @staticmethod
    def tiny(seq: int = 128) -> "LlamaConfig":
        return LlamaConfig(vocab_size=512, n_positions=seq, n_embd=128,
                           n_layer=2, n_head=4, n_kv_head=2,
                           intermediate=352, use_flash=False)

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


def _dense(features: int, axes: Tuple[str, ...], cfg: LlamaConfig, name: str):
    return nn.Dense(features, use_bias=False, dtype=cfg.dtype,
                    param_dtype=cfg.param_dtype,
                    kernel_init=nn.with_logical_partitioning(
                        nn.initializers.normal(0.02), axes),
                    name=name)


class RMSNorm(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale",
                           nn.with_logical_partitioning(
                               nn.initializers.ones, ("embed",)),
                           (x.shape[-1],), self.cfg.param_dtype)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                       keepdims=True)
        out = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.cfg.rms_eps)
        return (out * scale).astype(self.cfg.dtype)


def apply_rope(x: jnp.ndarray, positions: jnp.ndarray,
               theta: float) -> jnp.ndarray:
    """Rotary embedding on [b, heads, s, d] with per-token positions [b, s]
    (or [s]); rotates feature pairs (even, odd) halves-style."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    if positions.ndim == 1:
        positions = positions[None, :]
    ang = positions[:, None, :, None].astype(jnp.float32) * freqs  # [b,1,s,h]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, cache: Optional[Tuple] = None,
                 lora: Optional[Tuple] = None):
        """cache=None: full causal forward. cache=(k, v) with layout
        [b, max_len, kv_heads, head_dim]: write this call's K/V at each
        row's `positions` and attend over the cache; returns (x, cache').
        cache=(k_arena, v_arena, block_tables, write_mask) with arenas
        [num_blocks, block_size, kv_heads, head_dim]: paged variant —
        writes land at the physical slot the row's block table maps each
        position to (masked-off tokens go to trash block 0), reads gather
        the row's logical context back out of the arena.

        lora=(aq, bq, ao, bo, adapter_idx): model-multiplexed low-rank
        LATE-FUSION deltas (ladder-style side adapter). The block reads
        two backbone taps — the attn-normed input (aq/bq) and the
        flattened attention mixer output (ao/bo) — and returns their
        low-rank projection as a SIDE contribution instead of adding it
        to the residual stream; the caller accumulates the per-layer
        sides and merges the sum once, before the final norm. Because
        the residual stream itself is untouched, every layer's K/V is
        bit-identical to the base model's no matter which adapter ran:
        the paged arena is ADAPTER-INVARIANT and the radix prefix cache
        shares cached blocks across tenants exactly. (A classic
        in-place q/o delta would NOT have this property: perturbing one
        layer's output perturbs every deeper layer's K/V.) The banks
        hold one row per resident adapter ([n_rows, ...]; row 0 is the
        zero identity) and `adapter_idx` [b] routes each BATCH ROW to
        its adapter — routing is data, so one compiled program serves
        every adapter mix and loading/evicting an adapter never
        recompiles."""
        cfg = self.cfg
        hd = cfg.head_dim
        b, s, _ = x.shape
        h = RMSNorm(cfg, name="attn_norm")(x)
        q = _dense(cfg.n_head * hd, ("embed", "heads"), cfg, "wq")(h)
        k = _dense(cfg.n_kv_head * hd, ("embed", "heads"), cfg, "wk")(h)
        v = _dense(cfg.n_kv_head * hd, ("embed", "heads"), cfg, "wv")(h)
        q = q.reshape(b, s, cfg.n_head, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, cfg.n_kv_head, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, cfg.n_kv_head, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)

        groups = cfg.n_head // cfg.n_kv_head
        if cache is None:
            kf = jnp.repeat(k, groups, axis=1)
            vf = jnp.repeat(v, groups, axis=1)
            if cfg.sp_mesh is not None:
                from ray_tpu.ops.ring_attention import ring_attention_sharded

                # GQA repeat happens BEFORE the ring so every sequence
                # shard rotates full-head K/V chunks — same tensors the
                # flash path sees, so sp on/off is a pure schedule change.
                attn = ring_attention_sharded(q, kf, vf, cfg.sp_mesh,
                                              causal=True)
            elif cfg.use_flash:
                attn = flash_attention(q, kf, vf, True)
            else:
                attn = mha_reference(q, kf, vf, causal=True)
            new_cache = None
        elif len(cache) == 4:
            k_arena, v_arena, block_tables, write_mask = cache
            nb, bsz, kvh, _ = k_arena.shape
            max_blocks = block_tables.shape[1]
            max_ctx = max_blocks * bsz
            # Scatter this call's K/V into the arena. Physical slot of
            # logical position p in row i: block_tables[i, p // bsz] * bsz
            # + p % bsz. Masked tokens (batch padding, chunk padding) are
            # pointed at physical block 0 — reserved as a trash block the
            # manager never allocates — so one fixed-shape scatter handles
            # every mix of active/idle slots without recompiling.
            kw = k.transpose(0, 2, 1, 3).astype(k_arena.dtype)  # [b,s,kvh,d]
            vw = v.transpose(0, 2, 1, 3).astype(v_arena.dtype)
            blk = jnp.clip(positions // bsz, 0, max_blocks - 1)
            phys = jnp.take_along_axis(block_tables, blk, axis=1)  # [b, s]
            phys = jnp.where(write_mask, phys, 0)
            flat = (phys * bsz + positions % bsz).reshape(-1)
            k_flat = k_arena.reshape(nb * bsz, kvh, hd)
            v_flat = v_arena.reshape(nb * bsz, kvh, hd)
            k_flat = k_flat.at[flat].set(kw.reshape(-1, kvh, hd))
            v_flat = v_flat.at[flat].set(vw.reshape(-1, kvh, hd))
            # Gather each row's logical context back out of the arena.
            slot = (block_tables * bsz)[:, :, None] \
                + jnp.arange(bsz)[None, None, :]
            slot = slot.reshape(b, max_ctx)
            kf = jnp.repeat(k_flat[slot], groups, axis=2)  # [b,ctx,h,d]
            vf = jnp.repeat(v_flat[slot], groups, axis=2)
            # Causal over LOGICAL positions: arena slot (j, o) of a row
            # holds logical position j*bsz+o; unwritten slots sit past
            # every query's position (or behind trash-padded table
            # entries) and are masked out.
            kv_pos = jnp.arange(max_ctx)
            mask = kv_pos[None, None, :] <= positions[:, :, None]
            scores = jnp.einsum("bhqd,bkhd->bhqk",
                                q.astype(jnp.float32),
                                kf.astype(jnp.float32)) / (hd ** 0.5)
            scores = jnp.where(mask[:, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("bhqk,bkhd->bhqd", probs,
                              vf.astype(jnp.float32)).astype(cfg.dtype)
            new_cache = (k_flat.reshape(nb, bsz, kvh, hd),
                         v_flat.reshape(nb, bsz, kvh, hd),
                         block_tables, write_mask)
        else:
            k_cache, v_cache = cache                 # [b, max, kvh, d]
            max_len = k_cache.shape[1]
            rows = jnp.arange(b)[:, None]            # [b, 1]
            # positions is [b, s]: per-row write offsets (rows of a batch
            # may be at different lengths).
            k_cache = k_cache.at[rows, positions].set(
                k.transpose(0, 2, 1, 3).astype(k_cache.dtype))
            v_cache = v_cache.at[rows, positions].set(
                v.transpose(0, 2, 1, 3).astype(v_cache.dtype))
            kf = jnp.repeat(k_cache, groups, axis=2)  # [b, max, h, d]
            vf = jnp.repeat(v_cache, groups, axis=2)
            # Causal over absolute positions, per row: query at absolute
            # position p sees cache slots <= p; unwritten/pad slots are
            # beyond every query's position and masked out.
            kv_pos = jnp.arange(max_len)
            mask = kv_pos[None, None, :] <= positions[:, :, None]  # [b,s,max]
            scores = jnp.einsum("bhqd,bkhd->bhqk",
                                q.astype(jnp.float32),
                                kf.astype(jnp.float32)) / (hd ** 0.5)
            scores = jnp.where(mask[:, None], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("bhqk,bkhd->bhqd", probs,
                              vf.astype(jnp.float32)).astype(cfg.dtype)
            new_cache = (k_cache, v_cache)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_head * hd)
        out = _dense(cfg.n_embd, ("heads", "embed"), cfg, "wo")(attn)
        side = None
        if lora is not None:
            aq, bq, ao, bo, aidx = lora
            # Per-row bank gather, then two thin einsums per tap: the
            # delta path costs O(b*s*e*r) next to the dense O(b*s*e*f).
            # Compute in the model dtype end to end — bit-identical to a
            # dedicated replica running the same bank row alone. The sum
            # is RETURNED, never added to x: the residual stream (and so
            # the K/V written above) stays base-model-pure.
            s_in = jnp.einsum("bsr,bre->bse",
                              jnp.einsum("bse,ber->bsr", h, aq[aidx]),
                              bq[aidx])
            s_attn = jnp.einsum("bsr,bre->bse",
                                jnp.einsum("bsf,bfr->bsr", attn, ao[aidx]),
                                bo[aidx])
            side = (s_in + s_attn).astype(cfg.dtype)
        x = x + out

        h2 = RMSNorm(cfg, name="mlp_norm")(x)
        gate = _dense(cfg.intermediate, ("embed", "mlp"), cfg, "w_gate")(h2)
        up = _dense(cfg.intermediate, ("embed", "mlp"), cfg, "w_up")(h2)
        h2 = nn.silu(gate) * up
        x = x + _dense(cfg.n_embd, ("mlp", "embed"), cfg, "w_down")(h2)
        return nn.with_logical_constraint(x, ("batch", "seq", "embed")), \
            new_cache, side


class Llama(nn.Module):
    config: LlamaConfig

    def setup(self):
        cfg = self.config
        self.embed = self.param(
            "embed",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         ("vocab", "embed")),
            (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        block = LlamaBlock
        if cfg.remat:
            block = nn.remat(LlamaBlock, static_argnums=())
        self.blocks = [block(cfg, name=f"layer_{i}")
                       for i in range(cfg.n_layer)]
        self.final_norm = RMSNorm(cfg, name="final_norm")
        self.lm_head = _dense(cfg.vocab_size, ("embed", "vocab"), cfg,
                              "lm_head")

    def __call__(self, input_ids):
        cfg = self.config
        b, s = input_ids.shape
        x = self.embed.astype(cfg.dtype)[input_ids]
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        positions = jnp.arange(s)
        for blk in self.blocks:
            x, _, _ = blk(x, positions)
        x = self.final_norm(x)
        logits = self.lm_head(x)
        return nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))

    def decode(self, input_ids, cache, row_pos):
        """Incremental forward: each row writes K/V at its own offset
        (`row_pos` [b]) and gets logits for its s tokens. One jitted
        program serves both multi-token prefill and 1-token decode."""
        cfg = self.config
        b, s = input_ids.shape
        x = self.embed.astype(cfg.dtype)[input_ids]
        positions = row_pos[:, None] + jnp.arange(s)[None, :]  # [b, s]
        new_cache = []
        for i, blk in enumerate(self.blocks):
            x, layer_cache, _ = blk(x, positions, cache=cache[i])
            new_cache.append(layer_cache)
        x = self.final_norm(x)
        return self.lm_head(x), new_cache

    def decode_paged(self, input_ids, arenas, block_tables, row_pos,
                     write_mask, lora_banks=None, adapter_idx=None):
        """Step-shaped paged decode: the continuous-batching engine's
        entry point. `input_ids` [b, s] are each row's next s tokens
        (s = 1 for decode steps, s = chunk for chunked prefill),
        `arenas` is the per-layer [(k, v)] block arena shared by every
        sequence, `block_tables` [b, max_blocks] maps each row's logical
        blocks to physical ones, `row_pos` [b] is each row's first write
        position, and `write_mask` [b, s] zeroes batch/chunk padding
        (masked writes land in trash block 0). Returns (logits [b, s,
        vocab], new_arenas) — all shapes static, so one jitted program
        per (b, s) serves the engine forever.

        `lora_banks` (per-layer [(aq, bq, ao, bo)]) + `adapter_idx` [b]
        turn on model multiplexing: each batch row gets its adapter's
        per-layer low-rank LATE-FUSION deltas (row 0 = identity). Every
        layer contributes a side term read off the backbone's
        activations; the accumulated sum merges into the hidden state
        ONCE, before the final norm — the residual stream and all K/V
        writes stay base-model-pure, so cached prefix blocks are
        shareable across adapters exactly. The banks are fixed-shape
        arguments, so N adapters still compile the SAME two programs
        and adapter churn is pure data movement."""
        cfg = self.config
        b, s = input_ids.shape
        x = self.embed.astype(cfg.dtype)[input_ids]
        positions = row_pos[:, None] + jnp.arange(s)[None, :]  # [b, s]
        new_arenas = []
        side_sum = None
        for i, blk in enumerate(self.blocks):
            k_a, v_a = arenas[i]
            lora = None
            if lora_banks is not None:
                aq, bq, ao, bo = lora_banks[i]
                lora = (aq, bq, ao, bo, adapter_idx)
            x, layer_cache, side = blk(
                x, positions, cache=(k_a, v_a, block_tables, write_mask),
                lora=lora)
            if side is not None:
                side_sum = side if side_sum is None else side_sum + side
            new_arenas.append((layer_cache[0], layer_cache[1]))
        if side_sum is not None:
            x = x + side_sum.astype(x.dtype)
        x = self.final_norm(x)
        return self.lm_head(x), new_arenas


# --------------------------------------------------------------------------- #
# Pipeline stages: the model partitioned by layer for cross-process pp
# --------------------------------------------------------------------------- #


def stage_layer_ranges(cfg: LlamaConfig, pp: int):
    """[start, end) layer range per pipeline stage: near-even split, the
    remainder to the EARLIER stages (the last stage already carries the
    final norm + vocab-wide lm_head matmul)."""
    if not 1 <= pp <= cfg.n_layer:
        raise ValueError(f"pp={pp} must be in [1, n_layer={cfg.n_layer}]")
    base, rem = divmod(cfg.n_layer, pp)
    ranges, start = [], 0
    for s in range(pp):
        end = start + base + (1 if s < rem else 0)
        ranges.append((start, end))
        start = end
    return ranges


class LlamaStage(nn.Module):
    """One pipeline stage of :class:`Llama`: stage 0 owns the embedding
    + its layer range, the last stage its range + final norm + lm_head.
    Param names match the monolithic model exactly (``layer_{i}`` keeps
    the GLOBAL layer index), so a full checkpoint splits into stage
    trees — and re-groups across pp widths — by top-level key alone."""

    cfg: LlamaConfig
    stage: int
    pp: int

    def setup(self):
        cfg = self.cfg
        start, end = stage_layer_ranges(cfg, self.pp)[self.stage]
        if self.stage == 0:
            self.embed = self.param(
                "embed",
                nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                             ("vocab", "embed")),
                (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        block = LlamaBlock
        if cfg.remat:
            block = nn.remat(LlamaBlock, static_argnums=())
        self.blocks = [block(cfg, name=f"layer_{i}")
                       for i in range(start, end)]
        if self.stage == self.pp - 1:
            self.final_norm = RMSNorm(cfg, name="final_norm")
            self.lm_head = _dense(cfg.vocab_size, ("embed", "vocab"), cfg,
                                  "lm_head")

    def __call__(self, x):
        """Stage 0 takes token ids [b, s]; later stages take the
        previous stage's activations [b, s, embd]. The last stage
        returns logits, every other stage its boundary activations."""
        cfg = self.cfg
        if self.stage == 0:
            x = self.embed.astype(cfg.dtype)[x]
            x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        positions = jnp.arange(x.shape[1])
        for blk in self.blocks:
            x, _, _ = blk(x, positions)
        if self.stage == self.pp - 1:
            x = self.final_norm(x)
            logits = self.lm_head(x)
            return nn.with_logical_constraint(logits,
                                              ("batch", "seq", "vocab"))
        return x


def split_stage_params(params, cfg: LlamaConfig, pp: int):
    """Full param dict (``embed``/``layer_i``/``final_norm``/``lm_head``
    at top level) -> one per-stage dict per stage. Pure re-grouping:
    leaves are shared, never copied, and keys keep their global names —
    the inverse of :func:`merge_stage_params` at ANY pp width."""
    inner = params.get("params", params) if isinstance(params, dict) \
        else params
    out = []
    for s, (start, end) in enumerate(stage_layer_ranges(cfg, pp)):
        tree = {}
        if s == 0:
            tree["embed"] = inner["embed"]
        for i in range(start, end):
            tree[f"layer_{i}"] = inner[f"layer_{i}"]
        if s == pp - 1:
            tree["final_norm"] = inner["final_norm"]
            tree["lm_head"] = inner["lm_head"]
        out.append(tree)
    return out


def merge_stage_params(stage_trees):
    """Union of per-stage param dicts back into the full model tree
    (global key names make this a plain dict merge)."""
    out = {}
    for tree in stage_trees:
        dup = set(out) & set(tree)
        if dup:
            raise ValueError(f"stage trees overlap on {sorted(dup)} — "
                             "these are not disjoint stage splits")
        out.update(tree)
    return out


def _partition_rules():
    """The ``match_partition_rules`` regex table for llama params over a
    ("sp", "tp") stage mesh: column-parallel qkv/gate/up (output dim over
    tp), row-parallel wo/w_down (input dim over tp), vocab-sharded embed
    and lm_head, replicated norms. One table serves every stage subtree
    at every (tp, pp) width — rule paths are global param names."""
    from jax.sharding import PartitionSpec

    return (
        (r"embed$", PartitionSpec("tp")),
        (r"(wq|wk|wv)/kernel$", PartitionSpec(None, "tp")),
        (r"wo/kernel$", PartitionSpec("tp")),
        (r"(w_gate|w_up)/kernel$", PartitionSpec(None, "tp")),
        (r"w_down/kernel$", PartitionSpec("tp")),
        (r"lm_head/kernel$", PartitionSpec(None, "tp")),
        (r"(attn_norm|mlp_norm|final_norm)/scale$", PartitionSpec()),
    )


LLAMA_PARTITION_RULES = _partition_rules()


def shard_stage_params(stage_tree, mesh):
    """Place one stage's param subtree on its ("sp", "tp") stage mesh
    via the rule table (axes absent from the mesh prune to replicated,
    so tp=1 stage meshes work unchanged)."""
    from ray_tpu.parallel.sharding import shard_params_by_rules

    return shard_params_by_rules(stage_tree, mesh, LLAMA_PARTITION_RULES)


def make_paged_arena(cfg: LlamaConfig, num_blocks: int, block_size: int,
                     sharding=None):
    """Preallocated per-layer (k, v) paged arena [num_blocks, block_size,
    kv_heads, head_dim]. Block 0 is the trash block (never allocated to a
    sequence): masked writes land there and nothing ever reads it.
    `sharding` (from :func:`arena_sharding`) lays each arena out sharded
    on its kv-head dim — the paged cache shards WITH the attention heads,
    so a tp-sharded decode never gathers K/V across devices."""
    shape = (num_blocks, block_size, cfg.n_kv_head, cfg.head_dim)
    if sharding is None:
        def zeros():
            return jnp.zeros(shape, cfg.dtype)
    else:
        # Allocate DIRECTLY into the sharded layout: a device_put of a
        # host/default-device zeros array would transiently commit the
        # whole arena to one device — at real tp widths that excess can
        # OOM device 0 at startup even though the sharded steady state
        # fits. One jitted zeros program, executed 2*n_layer times.
        import jax

        zeros = jax.jit(lambda: jnp.zeros(shape, cfg.dtype),
                        out_shardings=sharding)
    return [(zeros(), zeros()) for _ in range(cfg.n_layer)]


# --------------------------------------------------------------------------- #
# LoRA adapter banks: model multiplexing on one compiled program set
# --------------------------------------------------------------------------- #


def lora_bank_shapes(cfg: LlamaConfig, n_rows: int, rank: int):
    """Per-layer bank shapes (aq, bq, ao, bo): one row per resident
    adapter, row 0 reserved as the zero identity. Both pairs are
    LATE-FUSION taps targeting the embedding: aq/bq read the block's
    attn-normed input, ao/bo the flattened attention mixer output. The
    deltas never enter the residual stream (they merge once, before the
    final norm), which keeps the paged KV arena adapter-invariant — the
    radix prefix cache shares cached blocks across tenants because of
    it."""
    return ((n_rows, cfg.n_embd, rank),
            (n_rows, rank, cfg.n_embd),
            (n_rows, cfg.n_head * cfg.head_dim, rank),
            (n_rows, rank, cfg.n_embd))


def lora_bank_shardings(cfg: LlamaConfig, mesh):
    """NamedShardings for one layer's (aq, bq, ao, bo) bank: ao's INPUT
    dim splits over "tp" WITH the flattened heads it reads (its rank-dim
    partial sums reduce exactly where wo's do); aq, bq and bo replicate
    (rank/embed dims are tiny or already replicated). Mirrors
    arena_sharding's no-trailing-None discipline so bank reloads can
    never perturb the jit cache key."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    validate_tp(cfg, _mesh_tp(mesh))
    del jax
    rep = NamedSharding(mesh, P())
    return (rep,
            rep,
            NamedSharding(mesh, P(None, "tp")),
            rep)


def make_adapter_weights(cfg: LlamaConfig, rank: int, seed: int,
                         scale: float = 0.05):
    """Deterministic per-layer LoRA rows from a seed: the SAME seed
    always yields the SAME weights, so a respawned replica reloading an
    adapter on demand — or a dedicated replica built for the parity
    proof — is bit-identical to the original. Returns per-layer
    (aq_row, bq_row, ao_row, bo_row) numpy arrays in the model dtype."""
    import numpy as np

    rng = np.random.default_rng(seed)
    dt = jnp.dtype(cfg.dtype)
    out = []
    for _ in range(cfg.n_layer):
        rows = []
        for shape in ((cfg.n_embd, rank), (rank, cfg.n_embd),
                      (cfg.n_head * cfg.head_dim, rank),
                      (rank, cfg.n_embd)):
            w = rng.standard_normal(shape, dtype=np.float32) * scale
            rows.append((w * 1.0).astype(dt))  # ml_dtypes casts in numpy
        out.append(tuple(rows))
    return out


# --------------------------------------------------------------------------- #
# Tensor-parallel path: NamedSharding placement over a "tp" mesh axis
# --------------------------------------------------------------------------- #


def validate_tp(cfg: LlamaConfig, tp: int) -> None:
    """Fail fast on widths XLA can't shard evenly: attention heads, KV
    heads (the paged arena shards with them), the SwiGLU hidden width and
    the vocab all split over tp."""
    bad = {name: dim for name, dim in (
        ("n_head", cfg.n_head), ("n_kv_head", cfg.n_kv_head),
        ("intermediate", cfg.intermediate), ("vocab_size", cfg.vocab_size))
        if dim % tp}
    if bad:
        raise ValueError(
            f"tp={tp} does not divide {bad} — pick a tp width that "
            "divides heads, kv heads, the MLP hidden and the vocab")


def tp_shardings(model: "Llama", mesh):
    """NamedSharding pytree for the params on `mesh` (logical axes ->
    mesh axes via the standard rules: heads/mlp/vocab shard over "tp")."""
    from ray_tpu.models.gpt2 import mesh_shardings_for

    return mesh_shardings_for(model, mesh, (1, 8))


def shard_params_tp(model: "Llama", params, mesh):
    """device_put an (un)sharded param pytree into its tp layout —
    resharding is a no-op placement when the layout already matches, so
    this is safe on freshly-initialized and checkpoint-restored trees
    alike."""
    import jax

    validate_tp(model.config, _mesh_tp(mesh))
    return jax.device_put(params, tp_shardings(model, mesh))


def arena_sharding(cfg: LlamaConfig, mesh):
    """NamedSharding for the paged KV arena: kv-head dim over "tp"
    ([num_blocks, block_size, kv_heads, head_dim] -> P(None, None, "tp",
    None)), the same split as the attention heads that read it."""
    import jax

    validate_tp(cfg, _mesh_tp(mesh))
    # No trailing None: jit normalizes output specs by dropping it, and a
    # device_put layout that differs only in the trailing None is a
    # DIFFERENT jit cache key — the engine's compile-once discipline
    # (fresh arenas after fail_all mixing with donated step outputs)
    # depends on the two being identical.
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(None, None, "tp"))


def _mesh_tp(mesh) -> int:
    axes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return int(axes.get("tp", 1))


def make_cache(cfg: LlamaConfig, batch: int, max_len: int):
    """Preallocated per-layer (k, v) cache [b, max_len, kv_heads, head_dim]
    (length-major so per-row writes are a single advanced-index set)."""
    shape = (batch, max_len, cfg.n_kv_head, cfg.head_dim)
    return [(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))
            for _ in range(cfg.n_layer)]


def flops_per_token(cfg: LlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token: 6N for the matmuls + attention term."""
    per_layer = (2 * cfg.n_embd * (cfg.n_head + 2 * cfg.n_kv_head)
                 * cfg.head_dim                       # qkv
                 + cfg.n_head * cfg.head_dim * cfg.n_embd  # out proj
                 + 3 * cfg.n_embd * cfg.intermediate)      # swiglu
    n = cfg.n_layer * per_layer + 2 * cfg.vocab_size * cfg.n_embd
    attn = 12 * cfg.n_layer * cfg.n_embd * seq_len
    return 6.0 * n + 2.0 * attn
