"""MLP: the smoke-test model for trainers, Tune, and multichip dryruns.

A plain flax MLP with the same logical-axis annotations as the flagship
GPT-2 ("embed"/"mlp" matmul axes over tp, "batch" over dp/fsdp), so every
sharding path exercised by the big model is exercised by the cheap one.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class MLP(nn.Module):
    """Multi-layer perceptron with logical sharding annotations.

    features: hidden layer widths; the final entry is the output width.
    """

    features: Sequence[int] = (128, 128, 10)
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[0], -1).astype(self.dtype)
        x = nn.with_logical_constraint(x, ("batch", "embed"))
        for i, width in enumerate(self.features):
            x = nn.Dense(
                width,
                dtype=self.dtype,
                param_dtype=self.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(),
                    ("embed", "mlp") if i % 2 == 0 else ("mlp", "embed")),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros,
                    ("mlp",) if i % 2 == 0 else ("embed",)),
                name=f"dense_{i}",
            )(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x


def classification_loss(logits, labels):
    """Mean softmax cross-entropy; labels are integer class ids."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


def make_train_step(model: nn.Module, optimizer):
    """Jitted (params, opt_state, batch) -> (params, opt_state, loss)."""
    import optax

    def step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply(p, batch["x"])
            return classification_loss(logits, batch["y"])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step)
