"""Sparse mixture-of-experts decoder (Mixtral-style), expert-parallel.

Third model family: a Llama-shaped decoder whose MLP is a top-k routed
mixture of SwiGLU experts. The reference has no MoE (Ray delegates model
parallelism to Alpa/DeepSpeed, `release/alpa_tests/train_opt_2_7b_minimum.py:39`);
this is net-new capability designed for the TPU from the start:

- **Static shapes everywhere.** Token-choice routing with a fixed expert
  capacity: dispatch and combine are dense one-hot einsums (the GSPMD MoE
  idiom), so XLA can tile them onto the MXU — no gather/scatter with
  data-dependent shapes, no host round-trips.
- **Experts shard over the `ep` mesh axis.** Expert weights are stacked
  `[n_experts, d, f]` tensors carrying the ("expert", ...) logical axis
  (rule "expert" -> ep in `parallel/sharding.DEFAULT_RULES`); dispatched
  activations are constrained to ("expert", None, "embed"), which makes XLA
  place the token all-to-all over the ep axis of the mesh (ICI).
- Router in float32 with an optional z-loss; Switch-style load-balancing
  auxiliary loss sown into a "losses" collection and added to the training
  objective by `make_moe_train_step`.

Attention/norm/embedding reuse the Llama components so tp/sp/fsdp behave
exactly as in the dense families.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ray_tpu.models.llama import RMSNorm, apply_rope, _dense
from ray_tpu.models.gpt2 import next_token_loss


@dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    n_positions: int = 2048
    n_embd: int = 1024
    n_layer: int = 8
    n_head: int = 16
    n_kv_head: int = 8
    intermediate: int = 2816         # per-expert SwiGLU width
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25    # slots per expert = ceil(T*k*cf/E)
    aux_coef: float = 0.01           # Switch load-balance loss weight
    router_z_coef: float = 1e-3      # router logit magnitude control
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    use_flash: bool = True
    remat: bool = False

    @staticmethod
    def small() -> "MoEConfig":
        return MoEConfig()

    @staticmethod
    def tiny(seq: int = 128) -> "MoEConfig":
        return MoEConfig(vocab_size=512, n_positions=seq, n_embd=128,
                         n_layer=2, n_head=4, n_kv_head=2, intermediate=256,
                         n_experts=4, top_k=2, use_flash=False)

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head


def expert_capacity(cfg: MoEConfig, n_tokens: int) -> int:
    import math

    cap = math.ceil(n_tokens * cfg.top_k * cfg.capacity_factor
                    / cfg.n_experts)
    return max(cap, cfg.top_k)


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU experts with fixed capacity.

    Input/output [b, s, d]. Tokens overflowing an expert's capacity fall
    through the residual (their MLP contribution is zero) — standard
    Switch/GShard behavior that keeps every shape static.
    """

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, s, d = x.shape
        t = b * s
        e, k = cfg.n_experts, cfg.top_k
        cap = expert_capacity(cfg, t)

        wr = self.param(
            "router",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         ("embed", None)),
            (d, e), jnp.float32)
        # Stacked expert weights: leading dim carries the "expert" axis.
        def ew(name, shape_in, shape_out):
            return self.param(
                name,
                nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                             ("expert", "embed", "mlp")
                                             if shape_out == cfg.intermediate
                                             else ("expert", "mlp", "embed")),
                (e, shape_in, shape_out), cfg.param_dtype)

        w_gate = ew("w_gate", d, cfg.intermediate)
        w_up = ew("w_up", d, cfg.intermediate)
        w_down = ew("w_down", cfg.intermediate, d)

        xt = x.reshape(t, d)
        logits = xt.astype(jnp.float32) @ wr                   # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        gate_vals, gate_idx = jax.lax.top_k(probs, k)          # [T, k]
        # Mixtral renormalizes the selected gates to sum to one.
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

        # Capacity-bounded dispatch/combine tensors [T, E, C], built one
        # routing choice at a time so earlier choices fill slots first.
        dispatch = jnp.zeros((t, e, cap), jnp.float32)
        combine = jnp.zeros((t, e, cap), jnp.float32)
        fill = jnp.zeros((e,), jnp.int32)                       # slots used
        for j in range(k):
            onehot = jax.nn.one_hot(gate_idx[:, j], e)          # [T, E] f32
            pos = (jnp.cumsum(onehot, axis=0) - 1.0
                   + fill[None, :].astype(jnp.float32))         # queue slot
            keep = (pos < cap) * onehot                         # dropped past C
            slot = jax.nn.one_hot(pos.astype(jnp.int32), cap)   # [T, E, C]
            dispatch = dispatch + keep[..., None] * slot
            combine = combine + (keep * gate_vals[:, j:j + 1])[..., None] * slot
            fill = fill + jnp.sum(onehot, axis=0).astype(jnp.int32)

        # Switch aux loss: E * sum_e(token_frac_e * mean_prob_e) over the
        # top-1 assignment; z-loss controls router logit growth.
        top1 = jax.nn.one_hot(gate_idx[:, 0], e)
        token_frac = jnp.mean(top1, axis=0)
        prob_mean = jnp.mean(probs, axis=0)
        aux = cfg.aux_coef * e * jnp.sum(token_frac * prob_mean)
        z = cfg.router_z_coef * jnp.mean(
            jnp.square(jax.nn.logsumexp(logits, axis=-1)))
        self.sow("losses", "aux_loss", aux + z)
        self.sow("intermediates", "dispatch", dispatch)
        self.sow("intermediates", "combine", combine)

        xd = jnp.einsum("tec,td->ecd", dispatch,
                        xt.astype(jnp.float32)).astype(cfg.dtype)
        xd = nn.with_logical_constraint(xd, ("expert", None, "embed"))
        gate = jnp.einsum("ecd,edf->ecf", xd, w_gate.astype(cfg.dtype))
        up = jnp.einsum("ecd,edf->ecf", xd, w_up.astype(cfg.dtype))
        h = nn.silu(gate) * up
        h = nn.with_logical_constraint(h, ("expert", None, "mlp"))
        out_e = jnp.einsum("ecf,efd->ecd", h, w_down.astype(cfg.dtype))
        y = jnp.einsum("tec,ecd->td", combine,
                       out_e.astype(jnp.float32)).astype(cfg.dtype)
        return y.reshape(b, s, d)


class MoEBlock(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        hd = cfg.head_dim
        b, s, _ = x.shape
        h = RMSNorm(cfg, name="attn_norm")(x)
        q = _dense(cfg.n_head * hd, ("embed", "heads"), cfg, "wq")(h)
        k = _dense(cfg.n_kv_head * hd, ("embed", "heads"), cfg, "wk")(h)
        v = _dense(cfg.n_kv_head * hd, ("embed", "heads"), cfg, "wv")(h)
        q = q.reshape(b, s, cfg.n_head, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, s, cfg.n_kv_head, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, s, cfg.n_kv_head, hd).transpose(0, 2, 1, 3)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        groups = cfg.n_head // cfg.n_kv_head
        kf = jnp.repeat(k, groups, axis=1)
        vf = jnp.repeat(v, groups, axis=1)
        if cfg.use_flash:
            from ray_tpu.ops.attention import flash_attention

            attn = flash_attention(q, kf, vf, True)
        else:
            from ray_tpu.ops.attention import mha_reference

            attn = mha_reference(q, kf, vf, causal=True)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, cfg.n_head * hd)
        x = x + _dense(cfg.n_embd, ("heads", "embed"), cfg, "wo")(attn)
        h2 = RMSNorm(cfg, name="mlp_norm")(x)
        x = x + MoEMLP(cfg, name="moe")(h2)
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class MoE(nn.Module):
    config: MoEConfig

    def setup(self):
        cfg = self.config
        self.embed = self.param(
            "embed",
            nn.with_logical_partitioning(nn.initializers.normal(0.02),
                                         ("vocab", "embed")),
            (cfg.vocab_size, cfg.n_embd), cfg.param_dtype)
        block = MoEBlock
        if cfg.remat:
            block = nn.remat(MoEBlock, static_argnums=())
        self.blocks = [block(cfg, name=f"layer_{i}")
                       for i in range(cfg.n_layer)]
        self.final_norm = RMSNorm(cfg, name="final_norm")
        self.lm_head = _dense(cfg.vocab_size, ("embed", "vocab"), cfg,
                              "lm_head")

    def __call__(self, input_ids):
        cfg = self.config
        b, s = input_ids.shape
        x = self.embed.astype(cfg.dtype)[input_ids]
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        positions = jnp.arange(s)
        for blk in self.blocks:
            x = blk(x, positions)
        x = self.final_norm(x)
        logits = self.lm_head(x)
        return nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))


def make_moe_train_step(model: MoE, optimizer, mesh=None,
                        donate: bool = True):
    """gpt2.make_train_step with an objective that adds the sown router
    losses (load balance + z) to the next-token cross-entropy (the
    displayed loss stays the plain CE so curves are comparable)."""
    from ray_tpu.models.gpt2 import make_train_step

    def loss_fn(p, batch):
        logits, aux_cols = model.apply(
            p, batch["input_ids"], mutable=["losses"])
        ce = next_token_loss(logits, batch["labels"])
        aux = sum(jax.tree.leaves(aux_cols.get("losses", {})),
                  jnp.float32(0.0))
        return ce + aux, ce

    return make_train_step(model, optimizer, mesh=mesh, donate=donate,
                           loss_fn=loss_fn)


def count_active_params(cfg: MoEConfig) -> int:
    """Parameters touched per token (dense weights + top_k experts)."""
    attn = cfg.n_embd * (cfg.n_head + 2 * cfg.n_kv_head) * cfg.head_dim \
        + cfg.n_head * cfg.head_dim * cfg.n_embd
    expert = 3 * cfg.n_embd * cfg.intermediate
    per_layer = attn + cfg.top_k * expert + cfg.n_embd * cfg.n_experts
    return cfg.n_layer * per_layer + 2 * cfg.vocab_size * cfg.n_embd


def flops_per_token(cfg: MoEConfig, seq_len: int) -> float:
    """Training FLOPs/token: 6x active params + attention term."""
    attn = 12 * cfg.n_layer * cfg.n_embd * seq_len
    return 6.0 * count_active_params(cfg) + 2.0 * attn
