"""ObjectRef: a future for a value in the distributed object store.

Equivalent of `ray.ObjectRef` (`python/ray/_raylet.pyx` ObjectRef): compares
and hashes by id, picklable (passing one to a task makes that task a
borrower), supports `future()`-style callbacks via the owning runtime.
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu.core.ids import ObjectID

# Active during task-arg serialization: ObjectRefs pickled INSIDE argument
# values (nested refs) are recorded here so the owner can pin them until
# the executing worker registers its borrow (reference: "contained object
# ids" collected by the serialization context, serialization.py).
_capture = threading.local()


class _NestedRefCapture:
    def __enter__(self):
        self._prev = getattr(_capture, "ids", None)
        _capture.ids = []
        return _capture.ids

    def __exit__(self, *exc):
        _capture.ids = self._prev


# Active during value DEserialization: refs reconstructed inside one
# pickle.loads register their borrows in a single batched GCS call at
# scope exit instead of one blocking round trip per ref (a value holding
# 1,000 refs would otherwise pay 1,000 RPCs before user code runs).
_borrow_scope = threading.local()


class _BorrowScope:
    def __enter__(self):
        self._outermost = getattr(_borrow_scope, "ids", None) is None
        if self._outermost:
            _borrow_scope.ids = []
        return self

    def __exit__(self, *exc):
        if not self._outermost:
            return
        ids, _borrow_scope.ids = _borrow_scope.ids, None
        if ids:
            rt = _current_runtime()
            if rt is not None:
                rt.on_refs_deserialized(ids)


class ObjectRef:
    __slots__ = ("object_id", "_owner_hint", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint: Optional[str] = None):
        self.object_id = object_id
        self._owner_hint = owner_hint
        rt = _current_runtime()
        if rt is not None:
            rt.register_ref(object_id)

    def binary(self) -> bytes:
        return self.object_id.binary()

    def hex(self) -> str:
        return self.object_id.hex()

    def task_id(self):
        return self.object_id.task_id()

    def __hash__(self):
        return hash(self.object_id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.object_id == self.object_id

    def __repr__(self):
        return f"ObjectRef({self.object_id.hex()})"

    def __reduce__(self):
        ids = getattr(_capture, "ids", None)
        if ids is not None:
            ids.append(self.object_id)
        return (_reconstruct_ref, (self.object_id.binary(), self._owner_hint))

    def __del__(self):
        try:
            rt = _current_runtime()
            if rt is not None:
                rt.deregister_ref(self.object_id)
        except Exception:
            pass

    def __await__(self):
        """Allow `await ref` inside async actors."""
        import asyncio

        async def _poll():
            import ray_tpu
            from ray_tpu.core import runtime as _rt

            # Unbounded by API contract (await has no deadline parameter)
            # — registered as ONE parked op for its whole duration so the
            # chaos HangWatchdog sees a wedged await as a hang, not as an
            # innocuous stream of 0-timeout polls.
            with _rt._ParkedOp(f"await {self.object_id.hex()[:12]}"):
                while True:
                    ready, _ = ray_tpu.wait([self], timeout=0)
                    if ready:
                        return ray_tpu.get(self)
                    await asyncio.sleep(0.002)

        return _poll().__await__()

    def future(self):
        """A concurrent.futures.Future resolving to the value."""
        import concurrent.futures
        import threading

        import ray_tpu

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(ray_tpu.get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut


def _current_runtime():
    import ray_tpu

    return getattr(ray_tpu, "_global_runtime", None)


def _reconstruct_ref(binary: bytes, owner_hint):
    # Deserializing a ref makes this process a borrower: the object must
    # survive the owner's free until this process drops it (reference
    # reference_count.h borrower protocol). Inside a _BorrowScope the
    # registration batches; bare reconstructions register one-by-one.
    ref = ObjectRef(ObjectID(binary), owner_hint)
    ids = getattr(_borrow_scope, "ids", None)
    if ids is not None:
        ids.append(ref.object_id)
        return ref
    rt = _current_runtime()
    if rt is not None:
        rt.on_refs_deserialized([ref.object_id])
    return ref
