"""ray_tpu.observability: the distributed tracing plane.

See docs/OBSERVABILITY.md for the span API, the propagation contract and
the timeline workflow. Quick tour::

    from ray_tpu.observability import get_tracer

    with get_tracer().start_span("my.operation", attrs={"k": "v"}):
        ...  # children (tasks, actor calls, RPCs) join this trace

Exports land in the GCS and are served by the dashboard
(`/api/traces/<trace_id>`, `/api/timeline`) or the CLI
(`python -m ray_tpu.observability timeline`).
"""

from ray_tpu.observability.tracing import (  # noqa: F401
    NOOP_SPAN,
    FlightRecorder,
    Span,
    Tracer,
    capture,
    current_ctx,
    enabled,
    format_traceparent,
    get_tracer,
    parse_traceparent,
    refresh_from_config,
)
from ray_tpu.observability.export import (  # noqa: F401
    chrome_trace_events,
    span_tree,
)

__all__ = [
    "FlightRecorder", "NOOP_SPAN", "Span", "Tracer", "capture",
    "chrome_trace_events", "current_ctx", "enabled", "format_traceparent",
    "get_tracer", "parse_traceparent", "refresh_from_config", "span_tree",
]
