"""Tracing CLI: export the cluster timeline or one trace's span tree.

    python -m ray_tpu.observability timeline [--out timeline.json]
                                             [--window 300] [--limit N]
    python -m ray_tpu.observability trace <trace_id> [--out tree.json]

The GCS address comes from --address or the RAY_TPU_GCS_ADDRESS env var
(set for every cluster process; for a driver shell, pass it explicitly).
Load the timeline file in https://ui.perfetto.dev or chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _gcs_client(address: str):
    from ray_tpu.core.rpc import RpcClient

    return RpcClient(address, name="trace-cli->gcs")


def _resolve_address(args) -> str:
    addr = args.address or os.environ.get("RAY_TPU_GCS_ADDRESS")
    if not addr:
        sys.exit("no GCS address: pass --address HOST:PORT or set "
                 "RAY_TPU_GCS_ADDRESS")
    return addr


def _write(out_path: str, obj) -> None:
    text = json.dumps(obj)
    if out_path == "-":
        sys.stdout.write(text + "\n")
        return
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(text)
    print(f"wrote {out_path} ({len(text)} bytes)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m ray_tpu.observability")
    ap.add_argument("--address", default=None,
                    help="GCS address (default: $RAY_TPU_GCS_ADDRESS)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    tl = sub.add_parser("timeline",
                        help="export the Chrome trace-event timeline")
    tl.add_argument("--out", default="timeline.json",
                    help="output path, or - for stdout")
    tl.add_argument("--window", type=float, default=None,
                    help="only spans ending within the last WINDOW seconds")
    tl.add_argument("--limit", type=int, default=None,
                    help="cap on exported spans (newest win)")
    tr = sub.add_parser("trace", help="export one trace's span tree")
    tr.add_argument("trace_id")
    tr.add_argument("--out", default="-", help="output path (default stdout)")
    args = ap.parse_args(argv)

    from ray_tpu.observability import chrome_trace_events, span_tree

    gcs = _gcs_client(_resolve_address(args))
    try:
        if args.cmd == "timeline":
            resp = gcs.call("trace_timeline",
                            {"window_s": args.window, "limit": args.limit},
                            timeout=30)
            spans = resp.get("spans") or []
            if not spans:
                print("no spans recorded (is tracing_enabled on?)",
                      file=sys.stderr)
            _write(args.out, chrome_trace_events(spans))
            if resp.get("dropped"):
                print(f"note: GCS dropped {resp['dropped']} spans "
                      "(trace_gcs_max_spans)", file=sys.stderr)
        else:
            resp = gcs.call("trace_get", {"trace_id": args.trace_id},
                            timeout=30)
            _write(args.out, span_tree(resp.get("spans") or [],
                                       args.trace_id))
    finally:
        gcs.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
