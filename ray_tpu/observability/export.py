"""Trace exports: span trees and Chrome trace-event (Perfetto) JSON.

Spans arrive as flat dicts (see `tracing.Span.end` for the schema, plus a
``proc`` key the GCS stamps from the reporter id). Two consumers:

- :func:`span_tree` — the `/api/traces/<trace_id>` JSON: spans of one
  trace nested by parent_id, children sorted by start time.
- :func:`chrome_trace_events` — the `/api/timeline` payload: the Chrome
  trace-event format (`catapult` JSON, loadable in Perfetto / legacy
  chrome://tracing) with one track ("process") per reporting process and
  one thread row per recorded thread name.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


def span_tree(spans: List[Dict[str, Any]],
              trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Nest the given spans (optionally filtered to one trace) by
    parent_id. Spans whose parent is absent from the set are roots."""
    if trace_id is not None:
        spans = [s for s in spans if s.get("trace_id") == trace_id]
    by_id = {s["span_id"]: dict(s, children=[]) for s in spans}
    roots: List[Dict[str, Any]] = []
    for node in by_id.values():
        parent = by_id.get(node.get("parent_id"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def _sort(nodes):
        nodes.sort(key=lambda n: n.get("start") or 0.0)
        for n in nodes:
            _sort(n["children"])
    _sort(roots)
    return {"trace_id": trace_id, "span_count": len(spans), "roots": roots}


def chrome_trace_events(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Render spans as Chrome trace events.

    Every span becomes one complete ("X") event; pids/tids are stable
    small integers with process_name / thread_name metadata events so the
    viewer shows the reporter id and thread name. Timestamps are epoch
    microseconds (Perfetto handles the large offsets fine).
    """
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        proc = s.get("proc") or "unknown"
        pid = pids.get(proc)
        if pid is None:
            pid = pids[proc] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": proc}})
        thread = s.get("thread") or "main"
        tid = tids.get((proc, thread))
        if tid is None:
            tid = tids[(proc, thread)] = \
                sum(1 for k in tids if k[0] == proc) + 1
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid, "args": {"name": thread}})
        start = float(s.get("start") or 0.0)
        end = float(s.get("end") or start)
        args: Dict[str, Any] = {"trace_id": s.get("trace_id"),
                                "span_id": s.get("span_id"),
                                "parent_id": s.get("parent_id")}
        if s.get("attrs"):
            args.update(s["attrs"])
        if s.get("error"):
            args["error"] = s["error"]
        events.append({
            "ph": "X",
            "name": s.get("name") or "span",
            "cat": "ray_tpu" + (",error" if s.get("error") else ""),
            "ts": start * 1e6,
            "dur": max(0.0, (end - start) * 1e6),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
