"""Distributed tracing: spans, context propagation, flight recorder.

The tracing plane gives the cluster per-request causality that the
aggregate counters in `ray_tpu.util.metrics` cannot: every cross-process
boundary (RPC framing, task specs, actor calls, serve requests, collective
ops, forge spawns, object pulls, inference engine phases) opens a named
span tied to one trace id, and the resulting span trees are exported as
JSON (`/api/traces/<id>`) or a Chrome trace-event timeline
(`/api/timeline`, Perfetto-loadable).

Design constraints (reference `ray/util/tracing/tracing_helper.py`, but
self-contained — no OpenTelemetry dependency):

- **Disabled is near-free.** Every instrumentation site starts with a
  single module-bool guard; when `tracing_enabled` is off, `start_span`
  returns one shared no-op singleton and nothing allocates.
- **Bounded memory.** Spans land in a per-process ring buffer (the
  *flight recorder*): fixed capacity, drop-oldest with a drop counter.
  Spans that recorded an error are kept in a separate small ring so
  drop-oldest under a span storm cannot evict the evidence
  (always-sample-on-error at the buffer level).
- **Head-based sampling.** The sampling decision is made once, where a
  trace is rooted, and travels with the context (`sampled`); sampled-out
  requests return the no-op singleton everywhere downstream.
- **W3C-style propagation.** Context is `{trace_id, span_id, sampled}`;
  HTTP carries it as a `traceparent` header, internal RPC framing as a
  compact `t` envelope key, task specs as `spec.trace_ctx`.

Spans are flushed to the GCS piggybacked on the `MetricsPusher` cadence
(one RPC carries metrics + spans), so tracing adds no new background
threads or connections.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.config import GLOBAL_CONFIG

# --------------------------------------------------------------------- state

# Hot-path guard: instrumentation sites check this module bool before
# doing anything else. Refreshed from GLOBAL_CONFIG by refresh_from_config
# (called from ray_tpu.init / CoreRuntime startup, so workers pick the
# flag up from the propagated RAY_TPU_TRACING_ENABLED env).
_ENABLED: bool = False
_SAMPLE_RATE: float = 1.0

# Maps monotonic timestamps (the engine's Request clock) onto the epoch
# timeline every span uses.
_MONO_OFFSET = time.time() - time.monotonic()

# Process-global current trace context. A ContextVar, not a thread-local:
# async actor methods interleave on one event-loop thread and each asyncio
# task needs its own copy.
_trace_cv: "contextvars.ContextVar[Optional[Dict[str, Any]]]" = \
    contextvars.ContextVar("ray_tpu_trace", default=None)

# Shared singleton for "a context exists but the trace is sampled out":
# wire propagation restores it without allocating per request.
_UNSAMPLED_CTX: Dict[str, Any] = {"sampled": False}


def _rand_hex(nbytes: int) -> str:
    from ray_tpu.core.ids import _random_bytes

    return _random_bytes(nbytes).hex()


def epoch_of(monotonic_ts: float) -> float:
    """Translate a time.monotonic() stamp onto the span epoch timeline."""
    return monotonic_ts + _MONO_OFFSET


# ----------------------------------------------------------- flight recorder


class FlightRecorder:
    """Bounded per-process span buffer: fixed memory, drop-oldest.

    Error spans go to their own small ring so a storm of healthy spans
    cannot evict them before the next flush. All methods are leaf-locked
    (the recorder never calls out while holding its lock), so record()
    is safe from any context, including under control-plane locks.
    """

    ERROR_CAP = 256

    def __init__(self, cap: int = 4096):
        self._lock = threading.Lock()
        self._cap = max(1, int(cap))
        self._spans: deque = deque()
        self._errors: deque = deque()
        self._dropped = 0

    def resize(self, cap: int):
        with self._lock:
            self._cap = max(1, int(cap))
            while len(self._spans) > self._cap:
                self._spans.popleft()
                self._dropped += 1

    def record(self, span: Dict[str, Any]):
        with self._lock:
            if span.get("error") is not None:
                if len(self._errors) >= self.ERROR_CAP:
                    self._errors.popleft()
                    self._dropped += 1
                self._errors.append(span)
                return
            if len(self._spans) >= self._cap:
                self._spans.popleft()
                self._dropped += 1
            self._spans.append(span)

    def drain(self) -> Tuple[List[Dict[str, Any]], int]:
        """Pop every buffered span (errors first) + the drop count since
        the last drain. Called by the MetricsPusher flush."""
        with self._lock:
            spans = list(self._errors) + list(self._spans)
            self._errors.clear()
            self._spans.clear()
            dropped, self._dropped = self._dropped, 0
            return spans, dropped

    def restore(self, spans: List[Dict[str, Any]], dropped: int):
        """Put a failed flush's drained spans (and their drop count)
        back, so a GCS hiccup delays delivery instead of silently losing
        the spans AND the accounting. Still bounded: re-recording runs
        through the normal caps."""
        with self._lock:
            self._dropped += dropped
        for span in spans:
            self.record(span)

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans) + len(self._errors)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"buffered": len(self._spans) + len(self._errors),
                    "cap": self._cap, "dropped": self._dropped}


RECORDER = FlightRecorder(4096)


# -------------------------------------------------------------------- spans


class _NoopSpan:
    """Shared do-nothing span: the disabled/sampled-out path returns this
    exact singleton from every call site — no allocation."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set_attr(self, key: str, value: Any):
        return self

    def end(self, error: Optional[str] = None):
        pass

    @property
    def ctx(self) -> None:
        return None


NOOP_SPAN = _NoopSpan()


class Span:
    """One recorded operation. Use as a context manager; a raised
    exception marks the span errored. Ending restores the previous
    context, so nesting works naturally."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "attrs", "error", "_token", "_ctx")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: Optional[str],
                 attrs: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = time.time()
        self.attrs = dict(attrs) if attrs else None
        self.error: Optional[str] = None
        self._ctx = {"trace_id": trace_id, "span_id": span_id,
                     "sampled": True}
        self._token = _trace_cv.set(self._ctx)

    @property
    def ctx(self) -> Dict[str, Any]:
        """Propagation context for children of this span."""
        return self._ctx

    def set_attr(self, key: str, value: Any) -> "Span":
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and self.error is None:
            self.error = f"{exc_type.__name__}: {exc}"
        self.end()
        return False

    def end(self, error: Optional[str] = None):
        if self._token is None:
            return  # already ended (with-block + explicit end)
        if error is not None:
            self.error = error
        try:
            _trace_cv.reset(self._token)
        except ValueError:
            # Ended in a different context than it started (e.g. a span
            # handed across threads): current ctx is not ours to restore.
            pass
        self._token = None
        RECORDER.record({
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": time.time(),
            "thread": threading.current_thread().name,
            "attrs": self.attrs,
            "error": self.error,
        })


# ------------------------------------------------------------------- tracer


class Tracer:
    """Process-wide span factory. All methods are cheap no-ops while
    tracing is disabled; use :func:`get_tracer` for the singleton."""

    def start_span(self, name: str,
                   attrs: Optional[Dict[str, Any]] = None,
                   child_of: Optional[Dict[str, Any]] = None,
                   ctx: Optional[Dict[str, Any]] = None):
        """Open a span.

        - default: child of the current context; with no current context
          this roots a new trace (head sampling decides here).
        - ``child_of``: explicit parent context (e.g. parsed traceparent).
        - ``ctx``: ADOPT the ids in a pre-minted context (a task spec's
          ``trace_ctx``): the span IS that context's span, so the
          submitter-side ids and the executed span line up.

        Always use as a context manager or end() in a finally block —
        raylint RL008 flags anything else.
        """
        if not _ENABLED:
            return NOOP_SPAN
        if ctx is not None:
            if not ctx.get("sampled"):
                return NOOP_SPAN
            return Span(name, ctx["trace_id"], ctx["span_id"],
                        ctx.get("parent_span_id"), attrs)
        parent = child_of if child_of is not None else _trace_cv.get()
        if parent is None:
            if not self._sample():
                return NOOP_SPAN
            return Span(name, _rand_hex(16), _rand_hex(8), None, attrs)
        if not parent.get("sampled", False):
            return NOOP_SPAN
        return Span(name, parent["trace_id"], _rand_hex(8),
                    parent.get("span_id"), attrs)

    @staticmethod
    def _sample() -> bool:
        if _SAMPLE_RATE >= 1.0:
            return True
        if _SAMPLE_RATE <= 0.0:
            return False
        import random

        return random.random() < _SAMPLE_RATE

    def record_span(self, name: str, start: float, end: float,
                    ctx: Optional[Dict[str, Any]] = None,
                    parent_ctx: Optional[Dict[str, Any]] = None,
                    attrs: Optional[Dict[str, Any]] = None,
                    error: Optional[str] = None,
                    thread: Optional[str] = None):
        """Record a retrospective span from explicit timestamps (epoch
        seconds) — the engine's TTFT decomposition and the raylet's queue
        spans are reconstructed after the fact, not context-managed.

        ``ctx`` adopts ids (span IS the context); ``parent_ctx`` mints a
        fresh child span id under that parent. Unsampled/absent context
        records nothing.
        """
        if not _ENABLED:
            return
        if ctx is not None:
            if not ctx.get("sampled"):
                return
            trace_id, span_id = ctx["trace_id"], ctx["span_id"]
            parent_id = ctx.get("parent_span_id")
        elif parent_ctx is not None:
            if not parent_ctx.get("sampled"):
                return
            trace_id, span_id = parent_ctx["trace_id"], _rand_hex(8)
            parent_id = parent_ctx.get("span_id")
        else:
            return
        RECORDER.record({
            "name": name, "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id, "start": start, "end": end,
            "thread": thread or threading.current_thread().name,
            "attrs": dict(attrs) if attrs else None, "error": error,
        })


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


def enabled() -> bool:
    return _ENABLED


def refresh_from_config():
    """Re-read the tracing flags (called at runtime startup; workers see
    the driver's _system_config through the propagated env)."""
    global _ENABLED, _SAMPLE_RATE
    _ENABLED = bool(GLOBAL_CONFIG.tracing_enabled)
    _SAMPLE_RATE = float(GLOBAL_CONFIG.trace_sample_rate)
    RECORDER.resize(GLOBAL_CONFIG.trace_buffer_spans)


# ------------------------------------------------------ context propagation


def capture() -> Optional[Dict[str, Any]]:
    """Current trace context (None when disabled or no trace active) —
    stash it to re-enter the trace from another thread/queue."""
    if not _ENABLED:
        return None
    return _trace_cv.get()


def set_current(ctx: Optional[Dict[str, Any]]):
    """Install `ctx` as the current trace context (a task spec's
    trace_ctx, or a captured context crossing a thread boundary)."""
    _trace_cv.set(ctx)


def current_ctx() -> Optional[Dict[str, Any]]:
    return _trace_cv.get()


def child_spec_ctx() -> Dict[str, str]:
    """A fresh propagation context for a task spec being submitted from
    the current context: same trace (or a new sampled-or-not root), the
    current span as parent. Always returns ids — task events use them
    for timeline grouping even with tracing off."""
    span_id = _rand_hex(8)
    cur = _trace_cv.get()
    if cur and cur.get("trace_id"):
        return {"trace_id": cur["trace_id"], "span_id": span_id,
                "parent_span_id": cur.get("span_id"),
                "sampled": bool(cur.get("sampled"))}
    return {"trace_id": _rand_hex(16), "span_id": span_id,
            "parent_span_id": None,
            "sampled": bool(_ENABLED and Tracer._sample())}


# Wire form on RPC envelopes: key "t" is [trace_id, span_id] for a sampled
# context, or the int 0 for "context present but sampled out" (so the far
# side suppresses head sampling instead of re-rolling mid-trace).


def wire_ctx():
    """Compact trace context for the RPC envelope, or None."""
    ctx = _trace_cv.get()
    if ctx is None:
        return None
    if not ctx.get("sampled"):
        return 0
    return [ctx["trace_id"], ctx["span_id"]]


def activate(ctx: Optional[Dict[str, Any]]) -> "contextvars.Token":
    """Install `ctx` and return the token for :func:`deactivate` — for
    carrying a captured context across an executor/thread boundary."""
    return _trace_cv.set(ctx)


def activate_wire(t) -> "contextvars.Token":
    """Server side: install the envelope's wire context; returns the
    token for :func:`deactivate`."""
    if t == 0 or not isinstance(t, (list, tuple)) or len(t) < 2:
        return _trace_cv.set(_UNSAMPLED_CTX)
    return _trace_cv.set({"trace_id": t[0], "span_id": t[1],
                          "sampled": True})


def deactivate(token: "contextvars.Token"):
    try:
        _trace_cv.reset(token)
    except ValueError:
        pass


# --------------------------------------------------------- W3C traceparent


def parse_traceparent(header: Optional[str]) -> Optional[Dict[str, Any]]:
    """``00-<32 hex trace>-<16 hex span>-<2 hex flags>`` -> context dict
    (flags bit 0 = sampled), or None if malformed."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    try:
        flags = int(parts[3], 16)
        int(parts[1], 16)
        int(parts[2], 16)
    except ValueError:
        return None
    return {"trace_id": parts[1], "span_id": parts[2],
            "sampled": bool(flags & 1)}


def format_traceparent(ctx: Optional[Dict[str, Any]] = None
                       ) -> Optional[str]:
    """Render the current (or given) context as a traceparent header."""
    ctx = ctx if ctx is not None else _trace_cv.get()
    if not ctx or not ctx.get("trace_id"):
        return None
    flags = "01" if ctx.get("sampled") else "00"
    trace = ctx["trace_id"].ljust(32, "0")[:32]
    span = ctx["span_id"].ljust(16, "0")[:16]
    return f"00-{trace}-{span}-{flags}"


# ------------------------------------------------------------------- flush


def drain_for_flush() -> Tuple[List[Dict[str, Any]], int]:
    """(spans, dropped) since the last flush; empty when disabled (the
    recorder may still hold spans from a just-disabled session — drain
    them so memory is released)."""
    if not _ENABLED and not len(RECORDER):
        return [], 0
    return RECORDER.drain()
