"""Flash attention: Pallas TPU kernels, forward AND backward.

Net-new TPU capability (the reference has no kernel code — SURVEY.md §5.7).
Forward: blocked online softmax, never materializing the S x S score
matrix; saves per-row logsumexp for the backward. Backward: two blocked
kernels (dQ with K/V streaming; dK/dV with Q streaming) recomputing
probabilities from the saved logsumexp — memory stays O(block^2) for
training too, which is the whole point for long context.

Layout: q,k,v [batch, heads, seq, head_dim]; grids put batch*heads and the
output-block dim as parallel dimensions and stream the contraction dim as
the innermost "arbitrary" dim with VMEM scratch accumulators.

Set RAY_TPU_PALLAS_INTERPRET=1 to run the kernels in interpreter mode on
CPU (used by tests to cover kernel logic without a chip).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_STATS_LANES = 128  # TPU lane width: stats scratch is (block_q, 128)


def _interpret() -> bool:
    return os.environ.get("RAY_TPU_PALLAS_INTERPRET") == "1"


def _compiler_params_cls(pltpu):
    # jax >= 0.5 renamed TPUCompilerParams -> CompilerParams.
    return getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def mha_reference(q, k, v, causal: bool = True,
                  scale: Optional[float] = None) -> jax.Array:
    """XLA reference attention. q,k,v: [batch, heads, seq, head_dim]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qs, ks = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((qs, ks), dtype=bool), k=ks - qs)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


# --------------------------------------------------------------------------- #
# Forward kernel
# --------------------------------------------------------------------------- #


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def body():
        q = q_ref[0].astype(jnp.float32)              # [bq, d]
        k = k_ref[0].astype(jnp.float32)              # [bk, d]
        v = v_ref[0].astype(jnp.float32)              # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                         # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                        # [bq, bk]
        correction = jnp.exp(m_prev - m_new)          # [bq, 1]
        l_new = correction * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Skip blocks entirely above the diagonal.
        @pl.when(ki * block_k <= qi * block_q + (block_q - 1))
        def _run():
            body()
    else:
        body()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / denom).astype(o_ref.dtype)
        # Row stats kept lane-broadcast: lse is (bh, seq, LANES) in HBM so
        # its blocks are (8, 128)-tileable on TPU; the backward kernels read
        # lane 0. Costs seq*LANES*4B per (b,h) — negligible vs the KV cache
        # and the price of a layout XLA can tile.
        lse_ref[0] = m_scr[...] + jnp.log(
            jnp.maximum(l_scr[...], 1e-30))


def _flash_forward(q, k, v, causal: bool, scale: float,
                   block_q: int, block_k: int):
    """Returns (out [b,h,sq,d], lse [bh, sq, 1]).

    The kernel writes lse lane-broadcast as (bh, sq, LANES) so its blocks
    are (8,128)-tileable, but only lane 0 is returned — the saved training
    residual stays O(seq), not O(seq*128); the backward re-broadcasts
    transiently."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    bh = batch * heads
    q3 = q.reshape(bh, seq_q, d)
    k3 = k.reshape(bh, seq_k, d)
    v3 = v.reshape(bh, seq_k, d)
    nq = pl.cdiv(seq_q, block_q)
    nk = pl.cdiv(seq_k, block_k)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _STATS_LANES),
                         lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq_q, _STATS_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q3, k3, v3)
    return out.reshape(batch, heads, seq_q, d), lse[..., :1]


# --------------------------------------------------------------------------- #
# Backward kernels
# --------------------------------------------------------------------------- #


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, scale: float, causal: bool,
                   block_q: int, block_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]                        # [bq, 1] (lane 0)
        delta = delta_ref[0][:, :1]                    # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                           # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                  # [bq, bk]
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + (block_q - 1))
        def _run():
            body()
    else:
        body()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, block_q: int, block_k: int):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]                        # lane 0
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse)                           # [bq, bk]
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),           # p^T @ do -> [bk, d]
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                  # [bq, bk]
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),           # ds^T @ q -> [bk, d]
            preferred_element_type=jnp.float32)

    if causal:
        # Q blocks strictly above the diagonal contribute nothing to this
        # K block: skip when the last q row < first k row.
        @pl.when(qi * block_q + (block_q - 1) >= ki * block_k)
        def _run():
            body()
    else:
        body()

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, causal: bool, scale: float,
                    block_q: int, block_k: int):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    bh = batch * heads
    q3 = q.reshape(bh, seq_q, d)
    k3 = k.reshape(bh, seq_k, d)
    v3 = v.reshape(bh, seq_k, d)
    do3 = g.reshape(bh, seq_q, d)
    # delta_i = rowsum(dO * O) (the softmax-jacobian diagonal term),
    # broadcast over stats lanes like lse. Both broadcasts are transient
    # kernel inputs, not saved residuals.
    delta = jnp.sum(do3.astype(jnp.float32)
                    * out.reshape(bh, seq_q, d).astype(jnp.float32),
                    axis=-1, keepdims=True)
    delta = jnp.broadcast_to(delta, (bh, seq_q, _STATS_LANES))
    lse = jnp.broadcast_to(lse, (bh, seq_q, _STATS_LANES))
    nq = pl.cdiv(seq_q, block_q)
    nk = pl.cdiv(seq_k, block_k)

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                  block_q=block_q, block_k=block_k)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _STATS_LANES),
                         lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _STATS_LANES),
                         lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale,
                                   causal=causal, block_q=block_q,
                                   block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _STATS_LANES),
                         lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _STATS_LANES),
                         lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, seq_k, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse, delta)

    shape_q = (batch, heads, seq_q, d)
    shape_k = (batch, heads, seq_k, d)
    return (dq.reshape(shape_q), dk.reshape(shape_k), dv.reshape(shape_k))


# --------------------------------------------------------------------------- #
# Dispatch + custom VJP
# --------------------------------------------------------------------------- #


def pick_block_sizes(seq: int, d: int) -> tuple:
    """Block-size heuristic: biggest blocks that fit VMEM comfortably.
    VMEM budget ~16 MiB; fwd scratch ~ block_q*(2*LANES + d)*4B plus the
    q/k/v/o blocks. Asymmetric q=512/k=1024 measured fastest on v5e for
    d<=128 (fewer grid steps on the streamed contraction dim); shrink for
    bigger heads."""
    if d <= 128:
        bq, bk = 512, 1024
    elif d <= 256:
        bq, bk = 256, 256
    else:
        bq, bk = 128, 128
    while seq % bq and bq > 128:
        bq //= 2
    while seq % bk and bk > 128:
        bk //= 2
    return bq, bk


_PALLAS_STATUS: dict = {}  # (platform, bq, bk, d, dtype) -> bool
_PALLAS_ERRORS: dict = {}  # same key -> repr of the probe failure


def pallas_status() -> dict:
    """Observability for the kernel self-check: {config-key: ok} plus any
    probe errors. Empty until the first TPU dispatch attempt."""
    return {"status": dict(_PALLAS_STATUS), "errors": dict(_PALLAS_ERRORS)}


def _pallas_selfcheck(platform: str, block_q: int, block_k: int,
                      d: int, dtype, causal: bool) -> bool:
    """Compile+run the kernels once at the exact production configuration
    (block sizes, head dim, dtype); on any failure disable the Pallas path
    for that configuration. A lowering bug must degrade to the XLA
    fallback, never take down training (round-2 postmortem).

    The probe runs in a fresh thread: JAX's trace state is thread-local, so
    this executes eagerly (and can really catch compile errors) even when
    the caller is mid-trace inside the user's jit."""
    key = (platform, block_q, block_k, d, jnp.dtype(dtype).name, causal)
    if key in _PALLAS_STATUS:
        return _PALLAS_STATUS[key]
    import threading

    result = {}

    def probe():
        try:
            seq = max(2 * block_k, 2 * block_q)
            q = jnp.ones((1, 1, seq, d), dtype)
            out, lse = _flash_forward(q, q, q, causal, 0.125,
                                      block_q, block_k)
            grads = _flash_backward(q, q, q, out, lse, out, causal, 0.125,
                                    block_q, block_k)
            jax.block_until_ready(grads)
            result["ok"] = True
        except Exception as e:  # noqa: BLE001 — any lowering/runtime error
            result["ok"] = False
            result["err"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join()
    _PALLAS_STATUS[key] = result.get("ok", False)
    if not _PALLAS_STATUS[key]:
        # Loud degradation: falling back to the O(S^2) XLA path is ~2x
        # slower and must be diagnosable after the fact.
        import logging

        _PALLAS_ERRORS[key] = result.get("err", "probe thread died")
        logging.getLogger("ray_tpu.ops.attention").warning(
            "Pallas flash-attention self-check FAILED for %s — using the "
            "XLA fallback for this config: %s", key, _PALLAS_ERRORS[key])
    return _PALLAS_STATUS[key]


def _use_pallas(q, k, block_q: int, block_k: int,
                causal: bool = True) -> bool:
    if _interpret():
        ok_platform = True
    else:
        try:
            platform = q.devices().pop().platform if hasattr(q, "devices") \
                else jax.devices()[0].platform
        except Exception:
            platform = jax.default_backend()
        ok_platform = platform == "tpu" and _pallas_selfcheck(
            platform, block_q, block_k, q.shape[-1], q.dtype, causal)
    if not ok_platform:
        return False
    _, _, seq_q, d = q.shape
    seq_k = k.shape[2]
    # The kernel's causal mask assumes q and k positions share origin 0,
    # while mha_reference aligns sequence *ends* (tril k=ks-qs); restrict
    # the kernel to seq_q == seq_k so both paths agree, and validate k's
    # sequence length for block divisibility.
    return (seq_q == seq_k and seq_q % block_q == 0 and seq_k % block_k == 0
            and d % 64 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 0, block_k: int = 0) -> jax.Array:
    """Blocked attention. q,k,v: [batch, heads, seq, head_dim].

    Dispatches to the Pallas kernels on TPU (shapes permitting; block size 0
    = auto) and the XLA reference elsewhere. Fully differentiable with a
    flash backward — training memory stays O(seq * block).
    """
    out, _ = _attn_fwd_impl(q, k, v, causal, scale, block_q, block_k)
    return out


def _resolve(q, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    seq = q.shape[2]
    if not block_q or not block_k:
        block_q, block_k = pick_block_sizes(seq, q.shape[-1])
    return scale, min(block_q, seq), min(block_k, seq)


def _attn_fwd_impl(q, k, v, causal, scale, block_q, block_k):
    scale, bq, bk = _resolve(q, scale, block_q, block_k)
    if _use_pallas(q, k, bq, bk, causal):
        return _flash_forward(q, k, v, causal, scale, bq, bk)
    return mha_reference(q, k, v, causal=causal, scale=scale), None


def _attn_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _attn_fwd_impl(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _attn_bwd(causal, scale, block_q, block_k, residuals, g):
    q, k, v, out, lse = residuals
    scale_v, bq, bk = _resolve(q, scale, block_q, block_k)
    if lse is not None and _use_pallas(q, k, bq, bk, causal):
        return _flash_backward(q, k, v, out, lse, g, causal, scale_v, bq, bk)
    _, vjp = jax.vjp(lambda q, k, v: mha_reference(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_attn_fwd, _attn_bwd)
