"""Flash attention: Pallas TPU kernel with online softmax.

Net-new TPU capability (the reference has no kernel code — SURVEY.md §5.7):
a blocked attention forward that never materializes the S x S score matrix.
Blocks of Q sit in VMEM while K/V blocks stream through the innermost grid
dimension with running (max, denominator, accumulator) statistics; causal
blocks above the diagonal are skipped entirely.

Training uses a custom VJP whose backward recomputes attention under XLA
(flash-style backward kernel lands later; the forward is the inference and
benchmark hot path).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
_STATS_LANES = 128  # TPU lane width: stats scratch is (block_q, 128)


def mha_reference(q, k, v, causal: bool = True,
                  scale: Optional[float] = None) -> jax.Array:
    """XLA reference attention. q,k,v: [batch, heads, seq, head_dim]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        qs, ks = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((qs, ks), dtype=bool), k=ks - qs)
        logits = jnp.where(mask, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale: float, causal: bool, block_q: int, block_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def body():
        q = q_ref[0].astype(jnp.float32)              # [bq, d]
        k = k_ref[0].astype(jnp.float32)              # [bk, d]
        v = v_ref[0].astype(jnp.float32)              # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:, :1]                         # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                        # [bq, bk]
        correction = jnp.exp(m_prev - m_new)          # [bq, 1]
        l_new = correction * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * correction + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # Skip blocks entirely above the diagonal.
        @pl.when(ki * block_k <= qi * block_q + (block_q - 1))
        def _run():
            body()
    else:
        body()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc_scr[...] / denom).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal: bool, scale: float,
                   block_q: int, block_k: int) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq_q, d = q.shape
    seq_k = k.shape[2]
    bh = batch * heads
    q3 = q.reshape(bh, seq_q, d)
    k3 = k.reshape(bh, seq_k, d)
    v3 = v.reshape(bh, seq_k, d)
    nq = pl.cdiv(seq_q, block_q)
    nk = pl.cdiv(seq_k, block_k)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq_q, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, _STATS_LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(q3, k3, v3)
    return out.reshape(batch, heads, seq_q, d)


def _use_pallas(q, k, block_q: int, block_k: int) -> bool:
    try:
        platform = q.devices().pop().platform if hasattr(q, "devices") else \
            jax.devices()[0].platform
    except Exception:
        platform = jax.default_backend()
    if platform != "tpu":
        return False
    _, _, seq_q, d = q.shape
    seq_k = k.shape[2]
    # The kernel's causal mask assumes q and k positions share origin 0,
    # while mha_reference aligns sequence *ends* (tril k=ks-qs); restrict
    # the kernel to seq_q == seq_k so both paths agree, and validate k's
    # sequence length for block divisibility.
    return (seq_q == seq_k and seq_q % block_q == 0 and seq_k % block_k == 0
            and d % 64 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512) -> jax.Array:
    """Blocked attention. q,k,v: [batch, heads, seq, head_dim].

    Dispatches to the Pallas kernel on TPU (shapes permitting) and the XLA
    reference elsewhere. Differentiable: backward recomputes via XLA.
    """
    return _attn_fwd_impl(q, k, v, causal, scale, block_q, block_k)


def _attn_fwd_impl(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    seq = q.shape[2]
    bq, bk = min(block_q, seq), min(block_k, seq)
    if _use_pallas(q, k, bq, bk):
        return _flash_forward(q, k, v, causal, scale, bq, bk)
    return mha_reference(q, k, v, causal=causal, scale=scale)


def _attn_fwd(q, k, v, causal, scale, block_q, block_k):
    out = _attn_fwd_impl(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _attn_bwd(causal, scale, block_q, block_k, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(lambda q, k, v: mha_reference(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


flash_attention.defvjp(_attn_fwd, _attn_bwd)
