"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

Net-new capability vs the reference (no sequence parallelism anywhere in it
— SURVEY.md §5.7). Each device holds a sequence shard of Q/K/V; K/V shards
rotate around the ring via `jax.lax.ppermute` (compiled to ICI neighbor
transfers) while each device folds every K/V chunk into its local Q's online
softmax statistics. Peak memory is O(S/sp * S/sp) per step instead of
O(S^2), and the rotation overlaps with compute under XLA's async
collectives.

Training-ready: a custom VJP runs the ring AGAIN for the backward —
gradients dK/dV ride the rotating ring alongside their chunks (each chunk
returns home after a full cycle carrying its accumulated gradient), so
rotated K/V are never materialized across steps the way differentiating
through the forward's fori_loop would.

Use inside shard_map/pjit with `q,k,v` sharded over `axis_name` on the
sequence dimension (logical axis "seq" -> mesh axis "sp").
"""

from __future__ import annotations

import functools
import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _chunk_scores(q, k, q_offset, k_offset, causal: bool, scale: float):
    """Masked scores of local q against one k chunk. [b,h,sq,sk] f32."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    return s


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Attention over a ring of sequence shards.

    Must run inside a mapped context (shard_map / pjit-manual) where
    `axis_name` is a mesh axis and q/k/v carry this device's sequence shard:
    [batch, heads, seq_shard, head_dim].
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _ring_attention(q, k, v, axis_name, causal, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attention(q, k, v, axis_name, causal, scale):
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    ring_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    seq_shard = q.shape[2]
    q_offset = my_idx * seq_shard

    m0 = jnp.full(q.shape[:3] + (1,), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros(q.shape[:3] + (1,), dtype=jnp.float32)
    acc0 = jnp.zeros(q.shape, dtype=jnp.float32)

    def step(i, carry):
        m, l, acc, kv = carry
        k_cur, v_cur = kv
        # Chunk j currently held = (my_idx - i) mod ring  (kv rotates +1).
        src_idx = (my_idx - i) % ring_size
        k_offset = src_idx * seq_shard
        s = _chunk_scores(q, k_cur, q_offset, k_offset, causal, scale)
        m_c = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), _NEG_INF / 2)
        p = jnp.exp(s - m_c)
        l_c = jnp.sum(p, axis=-1, keepdims=True)
        acc_c = jnp.einsum("bhqk,bhkd->bhqd", p,
                           v_cur.astype(jnp.float32))
        m_new = jnp.maximum(m, m_c)
        corr_prev = jnp.exp(m - m_new)
        corr_c = jnp.exp(m_c - m_new)
        l_new = l * corr_prev + l_c * corr_c
        acc_new = acc * corr_prev + acc_c * corr_c
        rot = [(j, (j + 1) % ring_size) for j in range(ring_size)]
        k_next = jax.lax.ppermute(k_cur, axis_name, rot)
        v_next = jax.lax.ppermute(v_cur, axis_name, rot)
        return m_new, l_new, acc_new, (k_next, v_next)

    m, l, acc, _ = jax.lax.fori_loop(0, ring_size, step, (m0, l0, acc0, (k, v)))
    l_safe = jnp.maximum(l, 1e-30)
    out = (acc / l_safe).astype(q.dtype)
    lse = m + jnp.log(l_safe)                        # [b,h,sq,1]
    return out, lse


def _ring_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, scale, residuals, g):
    """Second ring pass: dK/dV accumulate on the rotating chunks and return
    home after a full cycle; dQ accumulates locally."""
    q, k, v, out, lse = residuals
    ring_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    seq_shard = q.shape[2]
    q_offset = my_idx * seq_shard
    do = g.astype(jnp.float32)
    # Softmax-jacobian diagonal term: delta_i = rowsum(dO * O).
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1, keepdims=True)

    dq0 = jnp.zeros(q.shape, dtype=jnp.float32)
    dk0 = jnp.zeros(k.shape, dtype=jnp.float32)
    dv0 = jnp.zeros(v.shape, dtype=jnp.float32)

    def step(i, carry):
        dq, ring = carry
        k_cur, v_cur, dk_cur, dv_cur = ring
        src_idx = (my_idx - i) % ring_size
        k_offset = src_idx * seq_shard
        s = _chunk_scores(q, k_cur, q_offset, k_offset, causal, scale)
        p = jnp.exp(s - lse)                          # [b,h,sq,sk]
        dv_cur = dv_cur + jnp.einsum("bhqk,bhqd->bhkd", p, do)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do, v_cur.astype(jnp.float32))
        ds = p * (dp - delta) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds,
                             k_cur.astype(jnp.float32))
        dk_cur = dk_cur + jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
        rot = [(j, (j + 1) % ring_size) for j in range(ring_size)]
        ring_next = tuple(jax.lax.ppermute(t, axis_name, rot)
                          for t in (k_cur, v_cur, dk_cur, dv_cur))
        return dq, ring_next

    dq, ring = jax.lax.fori_loop(0, ring_size, step,
                                 (dq0, (k, v, dk0, dv0)))
    _, _, dk, dv = ring
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_sharded(q, k, v, mesh, causal: bool = True,
                           scale: Optional[float] = None,
                           sp_axis: str = "sp") -> jax.Array:
    """Convenience wrapper: shard_map ring_attention over the mesh's sp axis.

    q,k,v: global [batch, heads, seq, head_dim] arrays (sharded or not);
    output matches the input sharding convention (seq over sp).
    """
    from jax.sharding import PartitionSpec as P

    if sp_axis not in mesh.axis_names or mesh.shape[sp_axis] == 1:
        from ray_tpu.ops.attention import flash_attention

        return flash_attention(q, k, v, causal, scale)
    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
    # No trailing None for head_dim: unspecified trailing dims are
    # replicated anyway, and a trailing-None spec produces a different
    # jit cache key than the normalized one (RL023; the PR-8 recompile).
    spec = P(data_axes, None, sp_axis)
    body = partial(ring_attention, axis_name=sp_axis, causal=causal,
                   scale=scale)
    try:
        fn = jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                           out_specs=spec, check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as _legacy

        fn = _legacy(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec, check_rep=False)
    return fn(q, k, v)
