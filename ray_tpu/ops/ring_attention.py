"""Ring attention: sequence/context parallelism over the `sp` mesh axis.

Net-new capability vs the reference (no sequence parallelism anywhere in it —
SURVEY.md §5.7). Each device holds a sequence shard of Q/K/V; K/V shards
rotate around the ring via `jax.lax.ppermute` (compiled to ICI neighbor
transfers) while each device folds every K/V chunk into its local Q's online
softmax statistics. Peak memory is O(S/sp * S/sp) per step instead of O(S^2),
and the rotation overlaps with compute under XLA's async collectives.

Use inside shard_map/pjit with `q,k,v` sharded over `axis_name` on the
sequence dimension (logical axis "seq" -> mesh axis "sp").
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _chunk_attend(q, k, v, q_offset, k_offset, causal: bool, scale: float):
    """Scores of local q against one k/v chunk with global-position masking.
    Returns (m, l, acc) partial statistics. Shapes: q [b,h,sq,d], k/v [b,h,sk,d].
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)                      # [b,h,sq,1]
    # Guard fully-masked rows (all -inf): exp(-inf - -inf) -> use safe m.
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_safe, l, acc


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Attention over a ring of sequence shards.

    Must run inside a mapped context (shard_map / pjit-manual) where
    `axis_name` is a mesh axis and q/k/v carry this device's sequence shard:
    [batch, heads, seq_shard, head_dim].
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    ring_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    seq_shard = q.shape[2]
    q_offset = my_idx * seq_shard

    m0 = jnp.full(q.shape[:3] + (1,), _NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros(q.shape[:3] + (1,), dtype=jnp.float32)
    acc0 = jnp.zeros(q.shape, dtype=jnp.float32)

    def step(i, carry):
        m, l, acc, kv = carry
        k_cur, v_cur = kv
        # Chunk j currently held = (my_idx - i) mod ring  (kv rotates +1).
        src_idx = (my_idx - i) % ring_size
        k_offset = src_idx * seq_shard
        m_c, l_c, acc_c = _chunk_attend(q, k_cur, v_cur, q_offset, k_offset,
                                        causal, scale)
        m_new = jnp.maximum(m, m_c)
        corr_prev = jnp.exp(m - m_new)
        corr_c = jnp.exp(m_c - m_new)
        l_new = l * corr_prev + l_c * corr_c
        acc_new = acc * corr_prev + acc_c * corr_c
        perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc_new, (k_next, v_next)

    m, l, acc, _ = jax.lax.fori_loop(0, ring_size, step, (m0, l0, acc0, (k, v)))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, causal: bool = True,
                           scale: Optional[float] = None,
                           sp_axis: str = "sp") -> jax.Array:
    """Convenience wrapper: shard_map ring_attention over the mesh's sp axis.

    q,k,v: global [batch, heads, seq, head_dim] arrays (sharded or not);
    output matches the input sharding convention (seq over sp).
    """
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    if sp_axis not in mesh.axis_names or mesh.shape[sp_axis] == 1:
        from ray_tpu.ops.attention import flash_attention

        return flash_attention(q, k, v, causal, scale)
    data_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
    spec = P(data_axes, None, sp_axis, None)
    fn = shard_map(
        partial(ring_attention, axis_name=sp_axis, causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return fn(q, k, v)
