"""TPU parallelism layer: meshes, shardings, distributed init, collectives.

This is the TPU-native replacement for the reference's NCCL/Gloo collective
layer (`python/ray/util/collective/`) and Train's `torch.distributed` process
groups (`python/ray/train/torch/config.py:69-113`): parallelism is expressed
as a named `jax.sharding.Mesh` + sharding annotations, and XLA compiles the
collectives onto ICI/DCN (SURVEY.md §5.7-5.8).
"""

from ray_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    local_mesh,
)
from ray_tpu.parallel.sharding import (
    logical_axis_rules,
    named_sharding,
    shard_params,
    with_logical_constraint,
)
from ray_tpu.parallel.distributed import (
    DistributedContext,
    initialize_distributed,
)

__all__ = [
    "MeshSpec", "build_mesh", "local_mesh", "logical_axis_rules",
    "named_sharding", "shard_params", "with_logical_constraint",
    "DistributedContext", "initialize_distributed",
]
