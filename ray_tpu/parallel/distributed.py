"""Multi-host JAX process-group formation.

This is the seam the reference fills with `torch.distributed.init_process_group`
over NCCL (`python/ray/train/torch/config.py:69,113`) — here it is coordinator
election + `jax.distributed.initialize`, after which every host sees the full
multi-host device set and `pjit` programs compile collectives over ICI/DCN.

Protocol (driven by train.JaxBackend over a worker group):
  1. rank 0 picks a free port -> coordinator address
  2. every worker calls `initialize_distributed(addr, world, rank)`
  3. each worker builds the same Mesh over `jax.devices()` (global)
"""

from __future__ import annotations

import logging
import os
import socket
from dataclasses import dataclass
from typing import Optional

logger = logging.getLogger(__name__)


@dataclass
class DistributedContext:
    coordinator_address: str
    num_processes: int
    process_id: int
    initialized: bool = False

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0


_ctx: Optional[DistributedContext] = None


def get_address_and_port() -> tuple:
    hostname = socket.gethostbyname(socket.gethostname())
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return hostname, port


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: int = 1,
    process_id: int = 0,
    local_device_ids: Optional[list] = None,
) -> DistributedContext:
    """Join the JAX process group. Single-process (num_processes=1) is a
    no-op beyond recording context — jax.devices() already sees local chips.

    Never call after any jax computation has run in this process (XLA
    backends are frozen after first use) — the framework guarantees this by
    doing it in `Backend.on_start` before user code (SURVEY.md §3.4).
    """
    global _ctx
    if _ctx is not None and _ctx.initialized:
        if (_ctx.coordinator_address == coordinator_address
                and _ctx.process_id == process_id):
            return _ctx
        raise RuntimeError("jax.distributed already initialized differently")
    ctx = DistributedContext(coordinator_address or "local",
                             num_processes, process_id)
    if num_processes > 1:
        import jax

        kwargs = {}
        if local_device_ids is not None:
            kwargs["local_device_ids"] = local_device_ids
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
        logger.info("jax.distributed initialized: rank %d/%d via %s",
                    process_id, num_processes, coordinator_address)
    ctx.initialized = True
    _ctx = ctx
    return ctx


def shutdown_distributed():
    global _ctx
    if _ctx is not None and _ctx.initialized and _ctx.num_processes > 1:
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    _ctx = None


def distributed_context() -> Optional[DistributedContext]:
    return _ctx


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()
