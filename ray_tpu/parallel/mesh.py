"""Device mesh construction: dp / fsdp / tp / sp / ep axes over ICI + DCN.

The mesh IS the communicator: where the reference creates NCCL groups
(`collective_group/nccl_collective_group.py`) we build a
`jax.sharding.Mesh` whose axes map onto the physical topology — fast ICI
axes for tensor/sequence parallelism, the slower DCN axis for cross-slice
data parallelism (the "How to Scale Your Model" recipe).

Axis conventions (used by models/, train/, rllib/):
  dp    data parallel (pure replication of params, sharded batch)
  fsdp  fully-sharded data parallel (params sharded over this axis too)
  pp    pipeline parallel (layer stages; GPipe microbatch schedule)
  tp    tensor/model parallel (matmul contraction sharding)
  sp    sequence/context parallel (ring attention shards over this)
  ep    expert parallel (MoE experts)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "tp", "ep")


@dataclass
class MeshSpec:
    """Declarative mesh: axis name -> size; -1 on at most one axis = infer.

    `dcn_axes` marks axes that cross slice boundaries (multi-slice data
    parallelism over DCN); they are laid out as the slowest-varying mesh
    dims so XLA routes their collectives over DCN and keeps tp/sp on ICI.
    """

    axes: Dict[str, int] = field(default_factory=dict)
    dcn_axes: Tuple[str, ...] = ()

    def resolved(self, n_devices: int) -> Dict[str, int]:
        axes = {k: v for k, v in self.axes.items() if v != 1 or k in ("dp",)}
        axes = dict(self.axes)
        unknown = [k for k, v in axes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError("at most one axis may be -1")
        known = math.prod(v for v in axes.values() if v != -1)
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {axes}")
            axes[unknown[0]] = n_devices // known
        if math.prod(axes.values()) != n_devices:
            raise ValueError(
                f"mesh {axes} does not cover {n_devices} devices")
        return axes

    def axis_names(self) -> Tuple[str, ...]:
        ordered = [a for a in AXIS_ORDER if a in self.axes]
        extra = [a for a in self.axes if a not in AXIS_ORDER]
        return tuple(ordered + extra)

    @staticmethod
    def data_parallel() -> "MeshSpec":
        return MeshSpec({"dp": -1})

    @staticmethod
    def fsdp(tp: int = 1) -> "MeshSpec":
        return MeshSpec({"fsdp": -1, "tp": tp})

    @staticmethod
    def for_training(dp: int = 1, fsdp: int = -1, tp: int = 1, sp: int = 1
                     ) -> "MeshSpec":
        axes = {"dp": dp, "fsdp": fsdp, "tp": tp}
        if sp != 1:
            axes["sp"] = sp
        return MeshSpec(axes)


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None):
    """Build a `jax.sharding.Mesh` from a MeshSpec.

    Multi-slice layout: DCN-crossing axes are placed as the leading
    (slowest-varying) dims so that consecutive devices along ICI axes are
    physically adjacent. Uses `mesh_utils.create_device_mesh` when the
    topology is a real TPU slice (it knows the physical torus); falls back
    to a plain reshape on CPU/virtual platforms.
    """
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    axes = spec.resolved(len(devices))
    names = spec.axis_names()
    shape = tuple(axes[n] for n in names)
    # Order: DCN axes slowest. Reorder names so dcn axes come first.
    if spec.dcn_axes:
        dcn = [n for n in names if n in spec.dcn_axes]
        ici = [n for n in names if n not in spec.dcn_axes]
        names = tuple(dcn + ici)
        shape = tuple(axes[n] for n in names)
    try:
        platform = devices[0].platform
    except Exception:
        platform = "cpu"
    if platform == "tpu":
        from jax.experimental import mesh_utils

        if spec.dcn_axes:
            dcn_shape = tuple(axes[n] if n in spec.dcn_axes else 1 for n in names)
            ici_shape = tuple(1 if n in spec.dcn_axes else axes[n] for n in names)
            mesh_devices = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices)
        else:
            mesh_devices = mesh_utils.create_device_mesh(shape, devices=devices)
    else:
        mesh_devices = np.asarray(devices).reshape(shape)
    return jax.sharding.Mesh(mesh_devices, names)


def local_mesh(axis_name: str = "dp"):
    """Single-host mesh over all visible devices on one axis."""
    import jax

    return build_mesh(MeshSpec({axis_name: -1}))


def mesh_shape_summary(mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def validate_divisibility(mesh, batch_size: int, seq_len: Optional[int] = None):
    """Fail fast on shapes XLA can't shard evenly (a silent perf cliff)."""
    shape = mesh_shape_summary(mesh)
    data_ways = shape.get("dp", 1) * shape.get("fsdp", 1)
    if batch_size % data_ways:
        raise ValueError(
            f"global batch {batch_size} not divisible by dp*fsdp={data_ways}")
    sp = shape.get("sp", 1)
    if seq_len is not None and seq_len % max(sp, 1):
        raise ValueError(f"sequence length {seq_len} not divisible by sp={sp}")
