"""Pipeline parallelism: GPipe microbatch schedule over the `pp` mesh axis.

The reference delegates pipeline parallelism to engines run on top of its
actors (Alpa/DeepSpeed, `release/alpa_tests/train_opt_2_7b_minimum.py:39`);
here it is a first-class transform built the TPU way:

- The model's repeated trunk (L identical layers) is stacked into per-leaf
  `[n_stages, layers_per_stage, ...]` arrays whose leading dim carries the
  "stage" logical axis (rule "stage" -> pp).
- `gpipe` wraps a single-layer apply into an SPMD program via `shard_map`:
  each device along pp holds one stage and scans its local layers; a
  `lax.scan` over `n_microbatches + n_stages - 1` ticks moves activations
  stage-to-stage with `ppermute`. Everything is statically shaped, and
  `jax.grad` through scan+ppermute yields the pipelined backward (1F1B-ish
  memory can be recovered with `remat_stage=True`, which wraps each stage
  in `jax.checkpoint`).
- Embedding/LM-head run outside the pipelined trunk in the surrounding
  GSPMD region, so dp/tp/sp compose with pp: the pipeline is over layers,
  XLA still shards each stage's matmuls over tp and its batch over dp.

The first-stage feed selects microbatch `t` while later ticks feed from
the ring; the last stage's outputs are collected tick-aligned and summed
back over pp (zeros elsewhere), which keeps the schedule a pure function
of statically-known indices — no data-dependent control flow under jit.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def stack_stage_params(layer_params: Any, n_stages: int) -> Any:
    """Reshape a scanned-layers pytree `[L, ...]` to `[P, L/P, ...]`."""

    def reshape(leaf):
        l = leaf.shape[0]
        if l % n_stages:
            raise ValueError(
                f"{l} layers not divisible by {n_stages} pipeline stages")
        return leaf.reshape(n_stages, l // n_stages, *leaf.shape[1:])

    return jax.tree.map(reshape, layer_params)


def unstack_stage_params(staged_params: Any) -> Any:
    """Inverse of `stack_stage_params`: `[P, L/P, ...]` -> `[L, ...]`."""
    return jax.tree.map(
        lambda leaf: leaf.reshape(leaf.shape[0] * leaf.shape[1],
                                  *leaf.shape[2:]),
        staged_params)


def gpipe(layer_fn: Callable[[Any, jnp.ndarray], jnp.ndarray], mesh,
          n_microbatches: int, axis: str = "pp",
          remat_stage: bool = False) -> Callable[[Any, jnp.ndarray],
                                                 jnp.ndarray]:
    """Build `(staged_params, x) -> y` running layer_fn's stack pipelined.

    `staged_params` leaves are `[P, L/P, ...]` (see stack_stage_params) and
    must enter sharded over `axis` on the leading dim; `x` is `[B, ...]`
    with B divisible by n_microbatches. The returned y equals the
    sequential application of all L layers (same math, pipelined
    schedule).
    """
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover — older jax
        from jax.experimental.shard_map import shard_map

    def stage_fn(stage_params, x):
        # Scan this stage's local layers in order.
        def body(h, p):
            return layer_fn(p, h), None

        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn)

    n_stages = mesh.shape[axis]

    def spmd(staged_params, x_mb):
        # Local views: params [1, L/P, ...] -> [L/P, ...]; x_mb is the full
        # [M, mb, ...] microbatched input (replicated over pp).
        stage_params = jax.tree.map(lambda a: a[0], staged_params)
        idx = jax.lax.axis_index(axis)
        m = x_mb.shape[0]
        ticks = m + n_stages - 1
        zero_mb = jnp.zeros_like(x_mb[0])

        def tick(buf, t):
            # Stage 0 feeds microbatch t (while available); other stages
            # consume what the ring delivered last tick.
            feed = jnp.where(t < m, x_mb[jnp.minimum(t, m - 1)], zero_mb)
            inp = jnp.where(idx == 0, feed, buf)
            out = stage_fn(stage_params, inp)
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            nxt = jax.lax.ppermute(out, axis, perm)
            return nxt, out

        _, outs = jax.lax.scan(tick, zero_mb, jnp.arange(ticks))
        # The last stage emitted microbatch j's output at tick j + P - 1.
        tail = jax.lax.dynamic_slice_in_dim(outs, n_stages - 1, m, axis=0)
        y = jnp.where(idx == n_stages - 1, tail, jnp.zeros_like(tail))
        return jax.lax.psum(y, axis)

    # Batch dim of each microbatch shards over dp(+fsdp): every dp slice
    # pipelines only its share of the batch (pp shards layers, dp shards
    # data — the composition the mesh promises).
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    x_spec = P(None, batch_axes if batch_axes else None)

    def run(staged_params, x_mb):
        in_specs = (jax.tree.map(lambda _: P(axis), staged_params), x_spec)
        try:
            mapped = shard_map(spmd, mesh=mesh, in_specs=in_specs,
                               out_specs=x_spec, check_vma=False)
        except TypeError:  # pragma: no cover — older jax uses check_rep
            mapped = shard_map(spmd, mesh=mesh, in_specs=in_specs,
                               out_specs=x_spec, check_rep=False)
        return mapped(staged_params, x_mb)

    return run


def to_microbatches(x: jnp.ndarray, n_microbatches: int) -> jnp.ndarray:
    """[B, ...] -> [M, B/M, ...]."""
    b = x.shape[0]
    if b % n_microbatches:
        raise ValueError(f"batch {b} not divisible by {n_microbatches}"
                         " microbatches")
    return x.reshape(n_microbatches, b // n_microbatches, *x.shape[1:])


def from_microbatches(y: jnp.ndarray) -> jnp.ndarray:
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])


# --------------------------------------------------------------------------- #
# A pipelined transformer LM built from functional blocks
# --------------------------------------------------------------------------- #
#
# The trunk blocks are written as pure functions over a params dict (rather
# than flax modules) so they run unmodified inside shard_map's per-device
# world; embed/head stay in the outer GSPMD region.


def init_block_params(key, d_model: int, n_head: int, d_ff: int,
                      dtype=jnp.float32) -> dict:
    ks = jax.random.split(key, 6)
    s = 0.02
    return {
        "ln1_scale": jnp.ones((d_model,), dtype),
        "ln2_scale": jnp.ones((d_model,), dtype),
        "qkv": jax.random.normal(ks[0], (d_model, 3 * d_model), dtype) * s,
        "proj": jax.random.normal(ks[1], (d_model, d_model), dtype) * s,
        "fc": jax.random.normal(ks[2], (d_model, d_ff), dtype) * s,
        "fc_out": jax.random.normal(ks[3], (d_ff, d_model), dtype) * s,
    }


def _rms(x, scale, eps=1e-5):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
            * scale).astype(x.dtype)


def block_apply(p: dict, x: jnp.ndarray, n_head: int) -> jnp.ndarray:
    """Pre-norm causal attention + MLP block on [b, s, d]."""
    b, s, d = x.shape
    hd = d // n_head
    h = _rms(x, p["ln1_scale"])
    qkv = h @ p["qkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, n_head, hd).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (hd ** 0.5)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask, scores, -1e30)
    attn = jnp.einsum("bhqk,bhkd->bhqd",
                      jax.nn.softmax(scores, axis=-1), v)
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, d)
    x = x + attn @ p["proj"]
    h2 = _rms(x, p["ln2_scale"])
    return x + jax.nn.gelu(h2 @ p["fc"]) @ p["fc_out"]


def init_pp_lm(key, vocab: int, d_model: int, n_layer: int, n_head: int,
               d_ff: int, n_positions: int, n_stages: int) -> dict:
    """Params for the pipelined LM: stacked trunk + embed/head."""
    kl, ke, kp, kh = jax.random.split(key, 4)
    layer_params = jax.vmap(
        lambda k: init_block_params(k, d_model, n_head, d_ff))(
            jax.random.split(kl, n_layer))
    return {
        "stages": stack_stage_params(layer_params, n_stages),
        "embed": jax.random.normal(ke, (vocab, d_model)) * 0.02,
        "pos": jax.random.normal(kp, (n_positions, d_model)) * 0.01,
        "head": jax.random.normal(kh, (d_model, vocab)) * 0.02,
    }


def make_pp_train_step(mesh, n_head: int, n_microbatches: int,
                       optimizer, remat_stage: bool = False,
                       axis: str = "pp"):
    """Jitted pipelined train step (params, opt_state, batch) -> (...).

    Stage weights stay sharded over pp; embed/head live in the outer GSPMD
    region (sharded by dp/tp rules as usual). Loss is next-token CE.
    """
    from ray_tpu.models.gpt2 import next_token_loss

    pipe = gpipe(functools.partial(_pp_block, n_head=n_head), mesh,
                 n_microbatches, axis=axis, remat_stage=remat_stage)

    def forward(params, input_ids):
        b, s = input_ids.shape
        x = params["embed"][input_ids] + params["pos"][None, :s]
        x_mb = to_microbatches(x, n_microbatches)
        y_mb = pipe(params["stages"], x_mb)
        y = from_microbatches(y_mb)
        return y @ params["head"]

    def step(params, opt_state, batch):
        def loss_fn(p):
            logits = forward(p, batch["input_ids"])
            return next_token_loss(logits, batch["labels"])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        import optax

        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    with mesh:
        return jax.jit(step), forward


def _pp_block(p, x, n_head):
    return block_apply(p, x, n_head)


def sequential_forward(params: dict, input_ids, n_head: int):
    """Reference: apply the same stacked layers without the pipeline."""
    b, s = input_ids.shape
    x = params["embed"][input_ids] + params["pos"][None, :s]
    layers = unstack_stage_params(params["stages"])

    def body(h, p):
        return block_apply(p, h, n_head), None

    x, _ = jax.lax.scan(body, x, layers)
    return x @ params["head"]


def stage_shardings(mesh, params: dict, axis: str = "pp"):
    """NamedSharding pytree: stages over pp, everything else replicated."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    staged = jax.tree.map(
        lambda _: NamedSharding(mesh, P(axis)), params["stages"])
    out = {k: jax.tree.map(lambda _: NamedSharding(mesh, P()), v)
           for k, v in params.items() if k != "stages"}
    out["stages"] = staged
    return out
