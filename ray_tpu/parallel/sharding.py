"""Logical-axis sharding rules and helpers.

Parameters and activations are annotated with *logical* axis names
("embed", "mlp", "heads", "batch", "seq", ...); rules map logical axes to
mesh axes (dp/fsdp/tp/sp). This is the GSPMD idiom: annotate, let XLA place
collectives — the replacement for the reference's hand-managed NCCL calls.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

# Default rules: FSDP shards embed dim; TP shards mlp/hidden + heads; SP
# shards sequence; batch over (dcn_dp +) dp + fsdp — dcn_dp is the
# cross-slice data-parallel axis of a multi-slice mesh (laid out
# slowest-varying by MeshSpec.dcn_axes so its gradient psum rides DCN).
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("dcn_dp", "dp", "fsdp")),
    ("seq", "sp"),
    ("embed", "fsdp"),
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv", None),
    ("vocab", "tp"),
    ("expert", "ep"),
    ("stage", "pp"),
    ("norm", None),
)


def logical_axis_rules(overrides: Optional[Dict[str, Any]] = None,
                       mesh_axes: Optional[Sequence[str]] = None
                       ) -> List[Tuple[str, Any]]:
    """Rules as (logical, mesh-axis) pairs. When `mesh_axes` is given, targets
    not present in the mesh are pruned (flax's logical_to_mesh raises on
    unknown axes; a dp-only mesh must still shard "batch")."""
    rules = dict(DEFAULT_RULES)
    if overrides:
        rules.update(overrides)
    if mesh_axes is not None:
        pruned = {}
        for logical, target in rules.items():
            if isinstance(target, (tuple, list)):
                kept = tuple(t for t in target if t in mesh_axes)
                pruned[logical] = kept if kept else None
            else:
                pruned[logical] = target if target in mesh_axes else None
        rules = pruned
    return list(rules.items())


def _spec_for(logical_axes: Sequence[Optional[str]], rules: Dict[str, Any],
              mesh_axes: Sequence[str]):
    import jax

    out = []
    used = set()
    for ax in logical_axes:
        target = rules.get(ax) if ax is not None else None
        if target is None:
            out.append(None)
            continue
        if isinstance(target, (tuple, list)):
            present = tuple(t for t in target if t in mesh_axes and t not in used)
            used.update(present)
            out.append(present if present else None)
        else:
            if target in mesh_axes and target not in used:
                used.add(target)
                out.append(target)
            else:
                out.append(None)
    return jax.sharding.PartitionSpec(*out)


def named_sharding(mesh, *logical_axes: Optional[str],
                   rules: Optional[Dict[str, Any]] = None):
    """NamedSharding for a value whose dims carry these logical axis names."""
    import jax

    rd = dict(DEFAULT_RULES)
    if rules:
        rd.update(rules)
    spec = _spec_for(logical_axes, rd, mesh.axis_names)
    return jax.sharding.NamedSharding(mesh, spec)


def with_logical_constraint(x, mesh, *logical_axes: Optional[str],
                            rules: Optional[Dict[str, Any]] = None):
    """Annotate an intermediate value inside jit (lax.with_sharding_constraint)."""
    import jax

    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, *logical_axes, rules=rules))


def shard_params(params, mesh, param_logical_axes,
                 rules: Optional[Dict[str, Any]] = None):
    """device_put a parameter pytree according to per-leaf logical axes.

    `param_logical_axes` is a pytree matching `params` whose leaves are
    tuples of logical axis names (or None for replicated).
    """
    import jax

    def place(p, axes):
        if axes is None:
            sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        else:
            sh = named_sharding(mesh, *axes, rules=rules)
        return jax.device_put(p, sh)

    return jax.tree.map(place, params, param_logical_axes,
                        is_leaf=lambda x: x is None)


def replicated(mesh):
    import jax

    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def batch_sharding(mesh):
    """Sharding for host data entering the program: batch over dp(+fsdp)."""
    return named_sharding(mesh, "batch")
