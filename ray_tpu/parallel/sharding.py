"""Logical-axis sharding rules and helpers.

Parameters and activations are annotated with *logical* axis names
("embed", "mlp", "heads", "batch", "seq", ...); rules map logical axes to
mesh axes (dp/fsdp/tp/sp). This is the GSPMD idiom: annotate, let XLA place
collectives — the replacement for the reference's hand-managed NCCL calls.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

# Default rules: FSDP shards embed dim; TP shards mlp/hidden + heads; SP
# shards sequence; batch over (dcn_dp +) dp + fsdp — dcn_dp is the
# cross-slice data-parallel axis of a multi-slice mesh (laid out
# slowest-varying by MeshSpec.dcn_axes so its gradient psum rides DCN).
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("dcn_dp", "dp", "fsdp")),
    ("seq", "sp"),
    ("embed", "fsdp"),
    ("mlp", "tp"),
    ("heads", "tp"),
    ("kv", None),
    ("vocab", "tp"),
    ("expert", "ep"),
    ("stage", "pp"),
    ("norm", None),
)


def logical_axis_rules(overrides: Optional[Dict[str, Any]] = None,
                       mesh_axes: Optional[Sequence[str]] = None
                       ) -> List[Tuple[str, Any]]:
    """Rules as (logical, mesh-axis) pairs. When `mesh_axes` is given, targets
    not present in the mesh are pruned (flax's logical_to_mesh raises on
    unknown axes; a dp-only mesh must still shard "batch")."""
    rules = dict(DEFAULT_RULES)
    if overrides:
        rules.update(overrides)
    if mesh_axes is not None:
        pruned = {}
        for logical, target in rules.items():
            if isinstance(target, (tuple, list)):
                kept = tuple(t for t in target if t in mesh_axes)
                pruned[logical] = kept if kept else None
            else:
                pruned[logical] = target if target in mesh_axes else None
        rules = pruned
    return list(rules.items())


def _spec_for(logical_axes: Sequence[Optional[str]], rules: Dict[str, Any],
              mesh_axes: Sequence[str]):
    import jax

    out = []
    used = set()
    for ax in logical_axes:
        target = rules.get(ax) if ax is not None else None
        if target is None:
            out.append(None)
            continue
        if isinstance(target, (tuple, list)):
            present = tuple(t for t in target if t in mesh_axes and t not in used)
            used.update(present)
            out.append(present if present else None)
        else:
            if target in mesh_axes and target not in used:
                used.add(target)
                out.append(target)
            else:
                out.append(None)
    return jax.sharding.PartitionSpec(*out)


def named_sharding(mesh, *logical_axes: Optional[str],
                   rules: Optional[Dict[str, Any]] = None):
    """NamedSharding for a value whose dims carry these logical axis names."""
    import jax

    rd = dict(DEFAULT_RULES)
    if rules:
        rd.update(rules)
    spec = _spec_for(logical_axes, rd, mesh.axis_names)
    return jax.sharding.NamedSharding(mesh, spec)


def with_logical_constraint(x, mesh, *logical_axes: Optional[str],
                            rules: Optional[Dict[str, Any]] = None):
    """Annotate an intermediate value inside jit (lax.with_sharding_constraint)."""
    import jax

    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, *logical_axes, rules=rules))


def shard_params(params, mesh, param_logical_axes,
                 rules: Optional[Dict[str, Any]] = None):
    """device_put a parameter pytree according to per-leaf logical axes.

    `param_logical_axes` is a pytree matching `params` whose leaves are
    tuples of logical axis names (or None for replicated).
    """
    import jax

    def place(p, axes):
        if axes is None:
            sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        else:
            sh = named_sharding(mesh, *axes, rules=rules)
        return jax.device_put(p, sh)

    return jax.tree.map(place, params, param_logical_axes,
                        is_leaf=lambda x: x is None)


def match_partition_rules(rules, params):
    """Map every param leaf to a PartitionSpec by regex over its
    "/"-joined tree path (the t5x/EasyLM idiom, the complement of the
    logical-axis rules above for trees whose modules carry no
    annotations — e.g. a checkpoint-restored stage subtree).

    `rules` is an ordered sequence of (regex, PartitionSpec); the FIRST
    pattern that `re.search`-matches a leaf's path wins. Scalars (ndim
    0) always replicate. A leaf no rule matches raises — a silent
    fall-through to replicated would quietly undo tp sharding on a
    renamed param."""
    import re

    import jax
    from jax.sharding import PartitionSpec

    def assign(path, leaf):
        parts = []
        for k in path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "name"):
                # flax boxed params (LogicallyPartitioned etc.) insert a
                # GetAttrKey('value') hop — transparent to rule paths,
                # so "embed$" matches boxed and unboxed trees alike.
                if k.name != "value":
                    parts.append(str(k.name))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        name = "/".join(parts)
        if getattr(leaf, "ndim", 0) == 0:
            return PartitionSpec()
        for pattern, spec in rules:
            if re.search(pattern, name):
                return spec
        raise ValueError(f"no partition rule matches param '{name}' — "
                         "add a rule (or an explicit catch-all) so the "
                         "placement stays deliberate")

    return jax.tree_util.tree_map_with_path(assign, params)


def shard_params_by_rules(params, mesh, rules):
    """device_put a param pytree into the layout `rules` assigns on
    `mesh` (specs whose axes the mesh lacks are pruned to replicated on
    those dims, so one rule table serves every (tp, sp) submesh)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    specs = match_partition_rules(rules, params)

    def place(leaf, spec):
        dims = []
        for dim in spec:
            axes = dim if isinstance(dim, tuple) else (dim,)
            kept = tuple(a for a in axes
                         if a is None or a in mesh.axis_names)
            kept = tuple(a for a in kept if a is not None)
            dims.append(kept if len(kept) > 1
                        else (kept[0] if kept else None))
        while dims and dims[-1] is None:
            dims.pop()                     # no trailing None (RL023)
        return jax.device_put(leaf, NamedSharding(mesh,
                                                  PartitionSpec(*dims)))

    return jax.tree.map(place, params, specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))


def replicated(mesh):
    import jax

    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())


def batch_sharding(mesh):
    """Sharding for host data entering the program: batch over dp(+fsdp)."""
    return named_sharding(mesh, "batch")
