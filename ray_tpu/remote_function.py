"""RemoteFunction: `@ray_tpu.remote` on a function.

Equivalent of `python/ray/remote_function.py` (`RemoteFunction._remote`): the
function is exported once to the GCS function table; each `.remote()` builds a
TaskSpec and submits through the runtime (spillback handled there).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional

from ray_tpu.core import serialization
from ray_tpu.core.common import TaskSpec, normalize_resources
from ray_tpu.core.config import GLOBAL_CONFIG
from ray_tpu.core.ids import TaskID
from ray_tpu.object_ref import ObjectRef

_VALID_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "memory", "resources", "num_returns",
    "max_retries", "retry_exceptions", "name", "scheduling_strategy",
    "runtime_env", "max_calls", "_metadata",
}


def _resolve_pg_strategy(options: Dict[str, Any], resources: Dict[str, float]):
    """Rewrite resources to placement-group bundle resource names and pin the
    task to the bundle's node (reference: BundleSpec resource formatting)."""
    from ray_tpu.util.placement_group import PlacementGroup
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
        PlacementGroupSchedulingStrategy,
    )

    strategy = options.get("scheduling_strategy")
    if not isinstance(strategy, PlacementGroupSchedulingStrategy):
        return resources, strategy, None, -1
    pg: PlacementGroup = strategy.placement_group
    idx = strategy.placement_group_bundle_index
    node_hex = pg._bundle_node_hex(idx)
    from ray_tpu.core.common import (
        pg_bundle_resource_name,
        pg_wildcard_resource_name,
    )

    renamed: Dict[str, float] = {}
    for r, amt in resources.items():
        if idx >= 0:
            renamed[pg_bundle_resource_name(r, idx, pg.id)] = amt
        else:
            renamed[pg_wildcard_resource_name(r, pg.id)] = amt
    return renamed, NodeAffinitySchedulingStrategy(node_hex, soft=False), pg.id, idx


class RemoteFunction:
    def __init__(self, function, options: Optional[Dict[str, Any]] = None):
        self._function = function
        self._options = dict(options or {})
        bad = set(self._options) - _VALID_OPTIONS
        if bad:
            raise ValueError(f"Invalid @remote options: {bad}")
        self._function_blob: Optional[bytes] = None
        # Per-(runtime, function) submit-path caches: the exported
        # function id (sha1 of the blob — constant per function) and the
        # normalized resource shape (constant per options dict). Keyed
        # by the exporting runtime's worker_id (NOT a weakref — a
        # RemoteFunction captured in a task closure must stay
        # picklable), so a fresh session re-exports to its GCS.
        self._function_id: Optional[str] = None
        self._cached_resources: Optional[Dict[str, float]] = None
        self._cached_rt_key = None  # worker_id of the exporting runtime
        self._name = getattr(function, "__qualname__", getattr(function, "__name__", "fn"))
        functools.update_wrapper(self, function)

    def options(self, **kwargs) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(kwargs)
        return RemoteFunction(self._function, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._name}' cannot be called directly; use "
            f"'{self._name}.remote()' or access the original via '.func'.")

    @property
    def func(self):
        return self._function

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node instead of submitting (reference
        `ray.dag`): compose with other bound nodes, run via execute()."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        import ray_tpu

        runtime = ray_tpu._require_runtime()
        opts = self._options
        if self._cached_rt_key != runtime.worker_id:
            # New session (or first call): (re-)export to this runtime's
            # GCS and rebuild the per-runtime caches.
            if self._function_blob is None:
                self._function_blob = serialization.dumps(self._function)
            self._function_id = runtime.export_function(self._function_blob)
            self._cached_resources = normalize_resources(
                num_cpus=opts.get("num_cpus"),
                num_gpus=opts.get("num_gpus"),
                num_tpus=opts.get("num_tpus"),
                memory=opts.get("memory"),
                resources=opts.get("resources"),
                default_cpus=1.0,
            )
            self._cached_rt_key = runtime.worker_id
        function_id = self._function_id
        # Fresh copy per spec: downstream (PG renaming, lease keying)
        # treats spec.resources as its own.
        resources = dict(self._cached_resources)
        resources, strategy, pg_id, bundle_idx = _resolve_pg_strategy(opts, resources)
        ser_args, kwargs_keys, nested_refs = runtime.serialize_args(args, kwargs)
        spec = TaskSpec(
            task_id=TaskID.for_task(runtime.job_id),
            job_id=runtime.job_id,
            name=opts.get("name") or self._name,
            function_id=function_id,
            function_blob=None,
            args=ser_args,
            kwargs_keys=kwargs_keys,
            num_returns=opts.get("num_returns", 1),
            resources=resources,
            max_retries=opts.get("max_retries", GLOBAL_CONFIG.task_max_retries),
            retry_exceptions=opts.get("retry_exceptions", False),
            scheduling_strategy=strategy,
            placement_group_id=pg_id,
            placement_group_bundle_index=bundle_idx,
            owner_address=runtime.worker_id.hex(),
            runtime_env=opts.get("runtime_env"),
            nested_refs=nested_refs,
        )
        return_ids = runtime.submit_task(spec)
        refs = [ObjectRef(oid) for oid in return_ids]
        if spec.num_returns == 1:
            return refs[0]
        return refs
