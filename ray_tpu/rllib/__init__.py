"""ray_tpu.rllib: reinforcement learning on the new-stack architecture.

Equivalent of the reference's RLModule/Learner/LearnerGroup/RolloutWorker
stack (`rllib/core/`, `rllib/evaluation/` — the new stack only, per
SURVEY.md §7 "keep the new stack only"), with the torch/DDP learner replaced
by a jitted JAX learner.
"""

from ray_tpu.rllib.env import (
    CartPoleVectorEnv,
    GymnasiumVectorEnv,
    VectorEnv,
    make_env,
)
from ray_tpu.rllib.impala import (
    IMPALA,
    IMPALAConfig,
    IMPALALearner,
    vtrace_returns,
)
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.ppo import PPO, PPOConfig, PPOLearner
from ray_tpu.rllib.rl_module import DiscretePolicyModule, RLModule, SpecDict
from ray_tpu.rllib.rollout import RolloutWorker, WorkerSet

__all__ = [
    "VectorEnv", "CartPoleVectorEnv", "GymnasiumVectorEnv", "make_env",
    "RLModule", "DiscretePolicyModule", "SpecDict",
    "Learner", "LearnerGroup", "RolloutWorker", "WorkerSet",
    "PPO", "PPOConfig", "PPOLearner",
    "IMPALA", "IMPALAConfig", "IMPALALearner", "vtrace_returns",
]
