"""ray_tpu.rllib: reinforcement learning on the new-stack architecture.

Equivalent of the reference's RLModule/Learner/LearnerGroup/RolloutWorker
stack (`rllib/core/`, `rllib/evaluation/` — the new stack only, per
SURVEY.md §7 "keep the new stack only"), with the torch/DDP learner replaced
by a jitted JAX learner.
"""

from ray_tpu.rllib.connectors import (
    Connector,
    ConnectorPipeline,
    FrameStack,
    GrayscaleResize,
    atari_connectors,
)
from ray_tpu.rllib.env import (
    CartPoleVectorEnv,
    CatchVectorEnv,
    ConnectorVectorEnv,
    GymnasiumVectorEnv,
    VectorEnv,
    make_env,
)
from ray_tpu.rllib.impala import (
    IMPALA,
    IMPALAConfig,
    IMPALALearner,
    vtrace_returns,
)
from ray_tpu.rllib.appo import APPO, APPOConfig, APPOLearner
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNLearner, QModule
from ray_tpu.rllib.external import PolicyClient, PolicyServer
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.offline import (
    BC,
    BCConfig,
    iter_learner_batches,
    read_batches,
    write_batches,
)
from ray_tpu.rllib.replay_buffers import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.ppo import PPO, PPOConfig, PPOLearner
from ray_tpu.rllib.rl_module import (
    ConvPolicyModule,
    DiscretePolicyModule,
    RLModule,
    SpecDict,
    build_module,
)
from ray_tpu.rllib.rollout import RolloutWorker, WorkerSet

__all__ = [
    "VectorEnv", "CartPoleVectorEnv", "CatchVectorEnv",
    "ConnectorVectorEnv", "GymnasiumVectorEnv", "make_env",
    "Connector", "ConnectorPipeline", "FrameStack", "GrayscaleResize",
    "atari_connectors",
    "RLModule", "DiscretePolicyModule", "ConvPolicyModule", "SpecDict",
    "build_module",
    "Learner", "LearnerGroup", "RolloutWorker", "WorkerSet",
    "PPO", "PPOConfig", "PPOLearner",
    "IMPALA", "IMPALAConfig", "IMPALALearner", "vtrace_returns",
    "DQN", "DQNConfig", "DQNLearner", "QModule",
    "ReplayBuffer", "PrioritizedReplayBuffer",
    "BC", "BCConfig", "write_batches", "read_batches",
    "iter_learner_batches",
]
