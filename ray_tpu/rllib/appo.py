"""APPO: asynchronous PPO on the IMPALA machinery.

Equivalent of the reference's `rllib/algorithms/appo/appo.py` (APPOConfig
extends ImpalaConfig; `appo_torch_policy.py` loss): IMPALA's async
sampling + V-trace off-policy correction, with PPO's clipped surrogate
computed against the behavior policy and a slow-moving TARGET policy
network providing the V-trace/KL anchor — the piece that keeps the
surrogate stable when rollouts lag many updates behind.

TPU-first: like the other learners, one jitted update fused by XLA; the
target params ride as an explicit jit argument (replicated under dp
sharding) so syncing the target never retraces.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, IMPALALearner


@dataclass
class APPOConfig(IMPALAConfig):
    clip_param: float = 0.4            # reference APPOConfig default
    use_kl_loss: bool = False
    kl_coeff: float = 1.0
    # Learner updates between target-network syncs (reference
    # target_update_frequency).
    target_update_frequency: int = 1

    def build(self) -> "APPO":
        return APPO(self)


class APPOLearner(IMPALALearner):
    """V-trace advantages + PPO clip, anchored on a target policy."""

    def __init__(self, module, config, seed: int = 0, **kw):
        import jax

        super().__init__(module, config, seed=seed, **kw)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self._updates_since_sync = 0
        if self.num_devices > 1:
            rep = self._rep_sharding
            self.target_params = jax.device_put(self.target_params, rep)
            self._update_appo = jax.jit(
                self._update_appo_impl,
                in_shardings=(rep, rep, rep, self._batch_sharding),
                out_shardings=(rep, rep, rep))
        else:
            self._update_appo = jax.jit(self._update_appo_impl)

    # The base sharded `update` path jits compute_loss(params, batch);
    # APPO's loss needs the target params as a separately-replicated jit
    # argument, so it owns its update fn and overrides update().

    def _appo_loss(self, params, target_params, batch):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        heads = self._fragment_forward(params, batch)
        cur_logp, vf, entropy = heads["logp"], heads["vf"], heads["entropy"]

        # Target-policy heads anchor the V-trace correction and the KL
        # (reference: vtrace runs on the target model's action
        # distribution; appo_torch_policy.py).
        tgt_heads = jax.lax.stop_gradient(
            self._fragment_forward(target_params, batch))
        tgt_logp = tgt_heads["logp"]

        vs, pg_adv = self._vtrace_advantages(tgt_logp, batch, vf,
                                             heads["vf_ext"])

        # PPO clip against the BEHAVIOR policy's logp (what generated
        # the samples), with V-trace-corrected advantages.
        ratio = jnp.exp(cur_logp - batch[sb.LOGP])
        surrogate = jnp.minimum(
            pg_adv * ratio,
            pg_adv * jnp.clip(ratio, 1 - cfg.clip_param,
                              1 + cfg.clip_param))
        policy_loss = -jnp.mean(surrogate)
        vf_loss = 0.5 * jnp.mean((vs - vf) ** 2)
        mean_entropy = jnp.mean(entropy)
        loss = policy_loss + cfg.vf_loss_coeff * vf_loss \
            - cfg.entropy_coeff * mean_entropy
        # Analytic KL(target || current) over full action distributions
        # (a sampled tgt_logp - cur_logp estimator is NOT a KL: its
        # gradient is a flat likelihood bonus on sampled actions and it
        # can go negative).
        if "logits" in heads and "logits" in tgt_heads:
            cur_all = jax.nn.log_softmax(heads["logits"])
            tgt_all = jax.nn.log_softmax(tgt_heads["logits"])
            kl = jnp.mean(jnp.sum(
                jnp.exp(tgt_all) * (tgt_all - cur_all), axis=-1))
        else:  # modules without full distributions: report, don't train
            kl = jnp.mean(tgt_logp - cur_logp)
        if cfg.use_kl_loss and "logits" in heads:
            loss = loss + cfg.kl_coeff * kl
        return loss, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                      "entropy": mean_entropy, "kl": kl,
                      "mean_ratio": jnp.mean(ratio)}

    def _update_appo_impl(self, params, target_params, opt_state, batch):
        import jax
        import optax

        (loss, metrics), grads = jax.value_and_grad(
            self._appo_loss, has_aux=True)(params, target_params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics["total_loss"] = loss
        metrics["grad_norm"] = optax.global_norm(grads)
        return params, opt_state, metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        prepared = self._prepare_batch(batch, axis=self.dp_axis)
        if prepared is None:
            return {}
        self.params, self.opt_state, metrics = self._update_appo(
            self.params, self.target_params, self.opt_state, prepared)
        self._updates_since_sync += 1
        if self._updates_since_sync >= self.config.target_update_frequency:
            self.sync_target()
        return {k: float(v) for k, v in metrics.items()}

    def sync_target(self):
        import jax

        self.target_params = jax.tree.map(lambda x: x, self.params)
        self._updates_since_sync = 0

    def get_state(self):
        import jax

        state = super().get_state()
        state["target_params"] = jax.device_get(self.target_params)
        return state

    def set_state(self, state):
        super().set_state(state)
        self.target_params = state.get("target_params", self.params)


class APPO(IMPALA):
    """Reference `appo.py`: the IMPALA training loop, APPO learner."""

    learner_cls = APPOLearner
