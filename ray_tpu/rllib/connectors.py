"""Observation connector pipeline: env-to-module preprocessing.

Equivalent of the reference's agent connectors (`rllib/connectors/agent/`):
composable transforms applied inside the rollout worker between the raw env
observation and the module input. TPU-first design choice: observations stay
uint8 through the sample batch and over the wire (4x smaller than float32);
normalization to [0,1] happens on-device inside the CNN module.

The Atari recipe (reference `atari_wrappers.py` / AtariPreprocessing):
GrayscaleResize(84, 84) >> FrameStack(4).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class Connector:
    """One observation transform. Stateful connectors (FrameStack) track
    per-env state and must reset rows when episodes end."""

    def output_shape(self, input_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        return input_shape

    def output_dtype(self, input_dtype) -> np.dtype:
        return input_dtype

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def reset_rows(self, rows: np.ndarray, first_obs: np.ndarray) -> None:
        """Episode boundary for `rows`; `first_obs` is the already-
        transformed-by-upstream first observation of the new episode."""


class GrayscaleResize(Connector):
    """[B, H, W, C] (or [B, H, W]) uint8 -> [B, h, w] uint8.

    Grayscale via luma weights; resize by area-mean when the factor is an
    integer (the Atari 210x160 -> 84x84 path uses index sampling), else
    nearest-index sampling — pure numpy, no cv2 dependency.
    """

    def __init__(self, h: int = 84, w: int = 84):
        self.h, self.w = h, w
        self._row_idx = None
        self._col_idx = None

    def output_shape(self, input_shape):
        return (self.h, self.w)

    def output_dtype(self, input_dtype):
        return np.uint8

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        if obs.ndim == 4:  # [B, H, W, C] -> luma
            gray = (obs[..., 0] * 0.299 + obs[..., 1] * 0.587
                    + obs[..., 2] * 0.114) if obs.shape[-1] == 3 \
                else obs.mean(axis=-1)
        else:
            gray = obs
        B, H, W = gray.shape
        if H % self.h == 0 and W % self.w == 0:
            fh, fw = H // self.h, W // self.w
            out = gray.reshape(B, self.h, fh, self.w, fw).mean(axis=(2, 4))
        else:
            if self._row_idx is None or len(self._row_idx) != self.h:
                self._row_idx = (np.arange(self.h) * H // self.h)
                self._col_idx = (np.arange(self.w) * W // self.w)
            out = gray[:, self._row_idx][:, :, self._col_idx]
        return out.astype(np.uint8)


class FrameStack(Connector):
    """[B, h, w] -> [B, h, w, k]: the last k frames along a new channel
    axis (nature-DQN temporal context). New episodes start with the first
    frame repeated k times."""

    def __init__(self, k: int = 4):
        self.k = k
        self._stack: Optional[np.ndarray] = None

    def output_shape(self, input_shape):
        return tuple(input_shape) + (self.k,)

    def peek(self, obs: np.ndarray) -> np.ndarray:
        """The stack __call__ WOULD produce, without committing — used for
        the true-final-obs bootstrap at episode ends."""
        if self._stack is None or self._stack.shape[:-1] != obs.shape:
            return np.repeat(obs[..., None], self.k, axis=-1)
        return np.concatenate([self._stack[..., 1:], obs[..., None]], axis=-1)

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        self._stack = self.peek(obs)
        return self._stack.copy()

    def reset_rows(self, rows, first_obs):
        if self._stack is not None and rows.size:
            self._stack[rows] = np.repeat(
                first_obs[rows][..., None], self.k, axis=-1)


class ConnectorPipeline(Connector):
    """Ordered composition of connectors."""

    def __init__(self, connectors: Sequence[Connector]):
        self.connectors: List[Connector] = list(connectors)

    def output_shape(self, input_shape):
        for c in self.connectors:
            input_shape = c.output_shape(input_shape)
        return input_shape

    def output_dtype(self, input_dtype):
        for c in self.connectors:
            input_dtype = c.output_dtype(input_dtype)
        return input_dtype

    def __call__(self, obs):
        for c in self.connectors:
            obs = c(obs)
        return obs

    # Episode-boundary handling for stateful stages lives in
    # ConnectorVectorEnv (the one component that knows auto-reset timing);
    # a second reset path here would drift from it.


def atari_connectors(h: int = 84, w: int = 84, stack: int = 4
                     ) -> ConnectorPipeline:
    """The standard Atari preprocessing stack (reference
    `tuned_examples/ppo/atari-ppo.yaml` env_config)."""
    return ConnectorPipeline([GrayscaleResize(h, w), FrameStack(stack)])
