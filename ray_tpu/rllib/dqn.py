"""DQN on the new stack: Q-module, double-Q learner, prioritized replay.

Equivalent of the reference's `rllib/algorithms/dqn/` (DQNConfig, target
network, double-Q, prioritized replay) rebuilt on the jitted JAX
Learner/RLModule stack: the TD update is one XLA program (double-Q argmax,
Huber loss, importance weighting, optimizer apply fused on device), the
target network is a second params pytree swapped by reference, and
exploration is epsilon-greedy with epsilon carried inside the synced
weights so rollout actors need no side-channel schedule state.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ray_tpu.rllib import sample_batch as sb
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.replay_buffers import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rllib.rl_module import (
    RLModule,
    SpecDict,
    _ConvPolicyValueNet,
    _PolicyValueNet,
    conv_spec_for,
)
from ray_tpu.rllib.rollout import WorkerSet

logger = logging.getLogger(__name__)


class QModule(RLModule):
    """Q-network module: the policy head's outputs ARE the Q-values.

    Weights are `{"net": flax_params, "epsilon": f32}` — epsilon rides in
    the synced pytree (zero gradient, untouched by the optimizer), so the
    algorithm's schedule reaches every rollout actor through the ordinary
    weight broadcast.
    """

    def __init__(self, spec: SpecDict, hidden: Sequence[int] = (64, 64)):
        import jax

        self.spec = spec
        self.hidden = tuple(hidden)
        if len(spec.shape()) >= 2:
            self.model = _ConvPolicyValueNet(
                n_actions=spec.n_actions, **conv_spec_for(spec.shape()[0]))
        else:
            self.model = _PolicyValueNet(hidden=self.hidden,
                                         n_actions=spec.n_actions)
        self._explore = jax.jit(self._explore_impl)
        self._greedy = jax.jit(self._greedy_impl)

    def init_params(self, rng) -> Any:
        import jax.numpy as jnp

        dtype = jnp.uint8 if len(self.spec.shape()) >= 2 else jnp.float32
        obs = jnp.zeros((1,) + self.spec.shape(), dtype)
        return {"net": self.model.init(rng, obs),
                "epsilon": jnp.float32(1.0)}

    def q_values(self, net_params, obs):
        q, _ = self.model.apply(net_params, obs)
        return q

    # -- pure functions (jit-safe) -------------------------------------------

    def _explore_impl(self, params, obs, rng):
        import jax
        import jax.numpy as jnp

        q = self.q_values(params["net"], obs)
        greedy = jnp.argmax(q, axis=-1)
        k_eps, k_act = jax.random.split(rng)
        random_a = jax.random.randint(k_act, greedy.shape, 0,
                                      self.spec.n_actions)
        explore = jax.random.uniform(k_eps, greedy.shape) < params["epsilon"]
        actions = jnp.where(explore, random_a, greedy)
        return actions, jnp.max(q, axis=-1)

    def _greedy_impl(self, params, obs):
        import jax.numpy as jnp

        q = self.q_values(params["net"], obs)
        return jnp.argmax(q, axis=-1), jnp.max(q, axis=-1)

    # -- rollout interface ----------------------------------------------------

    def forward_exploration(self, params, obs, rng):
        import numpy as _np

        actions, qmax = self._explore(params, obs, rng)
        zeros = _np.zeros(actions.shape, _np.float32)
        return {"actions": actions, "logp": zeros, "vf": qmax}

    def forward_inference(self, params, obs):
        actions, qmax = self._greedy(params, obs)
        return {"actions": actions, "vf": qmax}

    def __reduce__(self):
        return (QModule, (self.spec, self.hidden))


@dataclass
class DQNConfig:
    env: Any = "CartPole-v1"
    num_rollout_workers: int = 1
    num_envs_per_worker: int = 8
    rollout_fragment_length: int = 16
    buffer_capacity: int = 50_000
    prioritized_replay: bool = True
    prioritized_alpha: float = 0.6
    prioritized_beta: float = 0.4
    learning_starts: int = 1_000
    train_batch_size: int = 64
    updates_per_iteration: int = 16
    target_network_update_freq: int = 500   # env steps between target syncs
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_timesteps: int = 10_000
    gamma: float = 0.99
    lr: float = 5e-4
    grad_clip: float = 10.0
    double_q: bool = True
    hidden: tuple = (64, 64)
    seed: int = 0
    learner_mode: str = "local"
    num_learners: int = 1
    learner_resources: Optional[Dict[str, float]] = None
    num_cpus_per_worker: float = 0.4
    rollout_platform: Optional[str] = "cpu"
    connectors: Any = None

    def build(self) -> "DQN":
        return DQN(self)


class DQNLearner(Learner):
    """TD learner with a target network; `update_dqn` returns |TD| per
    sample so the prioritized buffer can reweight what it replays."""

    batch_update_methods = ("update", "update_many", "update_dqn")

    def __init__(self, module: QModule, config, seed: int = 0, **kw):
        import jax

        super().__init__(module, config, seed=seed, **kw)
        self.target_net = jax.tree.map(lambda x: x, self.params["net"])
        if self.num_devices > 1:
            rep = self._rep_sharding
            self.target_net = jax.device_put(self.target_net, rep)
            self._update_dqn = jax.jit(
                self._update_dqn_impl,
                in_shardings=(rep, rep, rep, self._batch_sharding),
                out_shardings=(rep, rep, rep, self._batch_sharding))
        else:
            self._update_dqn = jax.jit(self._update_dqn_impl)

    def _td_loss(self, params, target_net, batch):
        """One TD/Huber loss definition shared by compute_loss (Learner
        interface) and update_dqn (priority-replay path)."""
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        q = self.module.q_values(params["net"], batch[sb.OBS])
        q_taken = jnp.take_along_axis(
            q, batch[sb.ACTIONS][..., None].astype(jnp.int32),
            axis=-1)[..., 0]
        q_next_target = self.module.q_values(target_net, batch["next_obs"])
        if cfg.double_q:
            q_next_online = self.module.q_values(params["net"],
                                                 batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
            q_boot = jnp.take_along_axis(
                q_next_target, best[..., None], axis=-1)[..., 0]
        else:
            q_boot = jnp.max(q_next_target, axis=-1)
        not_done = 1.0 - batch[sb.DONES].astype(jnp.float32)
        targets = batch[sb.REWARDS] + cfg.gamma * not_done * q_boot
        td = q_taken - jax.lax.stop_gradient(targets)
        weights = batch.get("weights", jnp.ones_like(td))
        loss = jnp.mean(weights * optax.huber_loss(td, delta=1.0))
        return loss, (td, jnp.mean(q))

    def compute_loss(self, params, batch):
        """Learner-interface loss (reference learner.py:645 keeps one
        update path). The target params ride in the batch as
        `_target_net` — an explicit jit argument, injected by update();
        a closure over self.target_net would be baked in at trace time
        and go stale after sync_target()."""
        target_net = batch.get("_target_net", self.target_net)
        clean = {k: v for k, v in batch.items() if k != "_target_net"}
        loss, (td, q_mean) = self._td_loss(params, target_net, clean)
        return loss, {"td_loss": loss, "q_mean": q_mean}

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self.num_devices > 1:
            # The base sharded jit shards every batch leaf over dp; the
            # target net must stay replicated, so route through the
            # dedicated update whose jit takes it as its own argument.
            metrics, _ = self.update_dqn(batch)
            return metrics
        return super().update({**batch, "_target_net": self.target_net})

    def _update_dqn_impl(self, params, target_net, opt_state, batch):
        import jax
        import optax

        (loss, (td, q_mean)), grads = jax.value_and_grad(
            lambda p: self._td_loss(p, target_net, batch),
            has_aux=True)(params)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = {"td_loss": loss, "q_mean": q_mean,
                   "grad_norm": optax.global_norm(grads)}
        import jax.numpy as jnp

        return params, opt_state, metrics, jnp.abs(td)

    def update_dqn(self, batch: Dict[str, np.ndarray]):
        orig_n = len(next(iter(batch.values())))
        prepared = self._prepare_batch(batch, axis=0)
        if prepared is None:
            return {}, np.zeros(orig_n, np.float32)
        self.params, self.opt_state, metrics, td_abs = self._update_dqn(
            self.params, self.target_net, self.opt_state, prepared)
        from ray_tpu.rllib.learner import host_local_numpy

        td_abs = host_local_numpy(td_abs)
        if len(td_abs) < orig_n:
            # dp trim dropped tail rows; keep their replay priority at the
            # batch mean rather than zeroing them out.
            pad = np.full(orig_n - len(td_abs),
                          float(td_abs.mean()) if len(td_abs) else 1.0,
                          np.float32)
            td_abs = np.concatenate([td_abs, pad])
        return {k: float(v) for k, v in metrics.items()}, td_abs

    def sync_target(self):
        import jax

        self.target_net = jax.tree.map(lambda x: x, self.params["net"])

    def get_state(self):
        state = super().get_state()
        import jax

        state["target_net"] = jax.device_get(self.target_net)
        return state

    def set_state(self, state):
        super().set_state(state)
        self.target_net = state["target_net"]


class DQN:
    """The Algorithm: replay-driven off-policy training (reference
    `rllib/algorithms/dqn/dqn.py` training_step)."""

    def __init__(self, config: DQNConfig):
        from ray_tpu.rllib.env import make_env

        self.config = config
        # Probe the env locally for its spec (cheaper than a worker probe).
        probe = make_env(config.env, n_envs=1, seed=config.seed,
                         connectors=config.connectors)
        spec = SpecDict(probe.obs_dim, probe.n_actions,
                        tuple(probe.obs_shape))
        del probe
        module = QModule(spec, hidden=config.hidden)
        self.workers = WorkerSet(
            config.env, num_workers=config.num_rollout_workers,
            n_envs=config.num_envs_per_worker, hidden=config.hidden,
            seed=config.seed,
            num_cpus_per_worker=config.num_cpus_per_worker,
            jax_platform=config.rollout_platform,
            connectors=config.connectors,
            module=module)
        self.module = module
        self.learner_group = LearnerGroup(
            lambda **kw: DQNLearner(module, config, seed=config.seed, **kw),
            mode=config.learner_mode,
            resources=config.learner_resources,
            num_learners=config.num_learners)
        if config.prioritized_replay:
            self.buffer: ReplayBuffer = PrioritizedReplayBuffer(
                config.buffer_capacity, alpha=config.prioritized_alpha,
                beta=config.prioritized_beta, seed=config.seed)
        else:
            self.buffer = ReplayBuffer(config.buffer_capacity,
                                       seed=config.seed)
        self.iteration = 0
        self._timesteps = 0
        self._last_target_sync = 0
        self._sync_exploration_weights()

    # ------------------------------------------------------------- schedule

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._timesteps / max(1, cfg.epsilon_timesteps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def _sync_exploration_weights(self):
        import jax.numpy as jnp

        weights = self.learner_group.get_weights()
        weights["epsilon"] = jnp.float32(self._epsilon())
        self.workers.sync_weights(weights)

    # ------------------------------------------------------------- training

    def _transitions(self, batch: Dict[str, np.ndarray]
                     ) -> Dict[str, np.ndarray]:
        """Trajectory fragment [T*n] -> (s, a, r, s', done) columns.

        next_obs is the time-shifted obs with the fragment tail bootstrapped
        from `_last_obs` and done rows patched with the TRUE final obs (the
        rollout records them before auto-reset). The TD target masks on
        terminateds only: truncated episodes (time limits) still bootstrap
        from their real final state.
        """
        T, n = batch.pop("_shape")
        obs = batch[sb.OBS].reshape((T, n) + batch[sb.OBS].shape[1:])
        next_obs = np.concatenate(
            [obs[1:], batch["_last_obs"][None]],
            axis=0).reshape(batch[sb.OBS].shape)
        fo_at = batch.get("_final_obs_at")
        if fo_at is not None:
            next_obs[fo_at] = batch["_final_obs"]
        terminated = batch[sb.DONES] & ~batch[sb.TRUNCATEDS]
        return {
            sb.OBS: batch[sb.OBS],
            "next_obs": next_obs,
            sb.ACTIONS: batch[sb.ACTIONS],
            sb.REWARDS: batch[sb.REWARDS].astype(np.float32),
            sb.DONES: terminated,
        }

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        for frag in self.workers.sample(cfg.rollout_fragment_length):
            self._timesteps += sb.batch_size(frag)
            self.buffer.add(self._transitions(frag))
        sample_s = time.perf_counter() - t0

        metrics: Dict[str, float] = {}
        updates = 0
        t1 = time.perf_counter()
        if len(self.buffer) >= max(cfg.learning_starts, cfg.train_batch_size):
            for _ in range(cfg.updates_per_iteration):
                replay = self.buffer.sample(cfg.train_batch_size)
                idx = replay.pop("_batch_indices")
                metrics, td_abs = self._learner_update(replay)
                if isinstance(self.buffer, PrioritizedReplayBuffer):
                    self.buffer.update_priorities(idx, td_abs)
                updates += 1
        learn_s = time.perf_counter() - t1

        if self._timesteps - self._last_target_sync >= \
                cfg.target_network_update_freq and updates:
            self._learner_sync_target()
            self._last_target_sync = self._timesteps
        self._sync_exploration_weights()
        return {"sample_s": sample_s, "learn_s": learn_s,
                "updates": updates, "epsilon": self._epsilon(),
                "buffer_size": len(self.buffer), **metrics}

    def _learner_update(self, batch):
        # LearnerGroup.call is an actor-group fan-out, not an RpcClient:
        # "update_dqn" names a learner METHOD dispatched via getattr.
        return self.learner_group.call("update_dqn", batch)  # raylint: disable=RL014

    def _learner_sync_target(self):
        self.learner_group.call("sync_target")  # raylint: disable=RL014 — actor-group call

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        step_metrics = self.training_step()
        stats = self.workers.episode_stats()
        rewards = [s["episode_reward_mean"] for s in stats
                   if s["episode_reward_mean"] is not None]
        return {
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps,
            "episode_reward_mean": float(np.mean(rewards)) if rewards else None,
            **step_metrics,
        }

    # --------------------------------------------------------- checkpointing

    def save(self, path: str) -> str:
        import os
        import pickle

        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm.pkl"), "wb") as f:
            pickle.dump({"learner": self.learner_group.get_state(),
                         "timesteps": self._timesteps,
                         "iteration": self.iteration,
                         "buffer": self.buffer.state(),
                         "last_target_sync": self._last_target_sync}, f)
        return path

    def restore(self, path: str):
        import os
        import pickle

        with open(os.path.join(path, "algorithm.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self._timesteps = state["timesteps"]
        self.iteration = state["iteration"]
        if "buffer" in state:
            self.buffer.set_state(state["buffer"])
        self._last_target_sync = state.get("last_target_sync", 0)
        self._sync_exploration_weights()

    def stop(self):
        self.workers.shutdown()
        self.learner_group.shutdown()
