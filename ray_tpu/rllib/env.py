"""Vectorized environments for rollout workers.

Equivalent of the reference's env layer (`rllib/env/vector_env.py`) reduced
to the batch-first protocol the sampler needs:

    reset() -> obs [n_envs, obs_dim]
    step(actions [n_envs]) -> (obs, rewards, dones, infos)

with auto-reset on termination (done envs restart; the returned obs is the
fresh episode's first observation, reference `VectorEnv` semantics).

`CartPoleVectorEnv` is a pure-numpy vectorized CartPole (dynamics per the
classic Barto-Sutton-Anderson formulation) — the sampler hot loop stays in
numpy instead of stepping n Python envs. `GymnasiumVectorEnv` adapts any
gymnasium env id.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np


class VectorEnv:
    n_envs: int
    obs_dim: int
    n_actions: int
    max_episode_steps: int = 500

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Dict[str, Any]]:
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """Numpy-vectorized CartPole-v1 (same constants as gymnasium's)."""

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5           # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4

    def __init__(self, n_envs: int = 8, seed: int = 0,
                 max_episode_steps: int = 500):
        self.n_envs = n_envs
        self.obs_dim = 4
        self.n_actions = 2
        self.max_episode_steps = max_episode_steps
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((n_envs, 4), dtype=np.float64)
        self._steps = np.zeros(n_envs, dtype=np.int64)
        self._total_mass = self.MASSPOLE + self.MASSCART
        self._polemass_length = self.MASSPOLE * self.LENGTH

    def reset(self) -> np.ndarray:
        self._state = self._rng.uniform(-0.05, 0.05, size=(self.n_envs, 4))
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def _reset_envs(self, mask: np.ndarray):
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(-0.05, 0.05, size=(n, 4))
            self._steps[mask] = 0

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        costheta = np.cos(theta)
        sintheta = np.sin(theta)
        temp = (force + self._polemass_length * theta_dot ** 2 * sintheta
                ) / self._total_mass
        theta_acc = (self.GRAVITY * sintheta - costheta * temp) / (
            self.LENGTH * (4.0 / 3.0
                           - self.MASSPOLE * costheta ** 2 / self._total_mass))
        x_acc = temp - self._polemass_length * theta_acc * costheta \
            / self._total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1

        terminated = (np.abs(x) > self.X_LIMIT) | \
            (np.abs(theta) > self.THETA_LIMIT)
        truncated = (self._steps >= self.max_episode_steps) & ~terminated
        dones = terminated | truncated
        rewards = np.ones(self.n_envs, dtype=np.float32)
        # Auto-reset finished episodes; the truncated flag marks boundaries
        # where GAE should bootstrap V(next). Termination takes precedence
        # when both land on the same step (gymnasium/RLlib semantics).
        # final_obs carries the TRUE pre-reset state at done rows so value
        # bootstrapping at truncation uses the right state. Only built when
        # an episode actually ended — the hot loop stays allocation-lean.
        infos = {"truncated": truncated.copy()}
        if dones.any():
            infos["final_obs"] = self._state.astype(np.float32)
        self._reset_envs(dones)
        return (self._state.astype(np.float32), rewards, dones, infos)


class GymnasiumVectorEnv(VectorEnv):
    """Adapter over `gymnasium.make_vec` for arbitrary env ids."""

    def __init__(self, env_id: str, n_envs: int = 8, seed: int = 0, **kw):
        import gymnasium as gym

        # SAME_STEP autoreset so the obs returned at a done step is the new
        # episode's first observation (gymnasium 1.x defaults to NEXT_STEP,
        # which would inject a bogus no-op transition after every episode).
        # Native vector entry points reject vector_kwargs, so pin the sync
        # vectorizer, which honors autoreset_mode.
        try:
            kw.setdefault("vectorization_mode", "sync")
            kw.setdefault("vector_kwargs",
                          {"autoreset_mode": gym.vector.AutoresetMode.SAME_STEP})
        except AttributeError:
            pass  # older gymnasium: same-step is already the behavior
        self._env = gym.make_vec(env_id, num_envs=n_envs, **kw)
        self.n_envs = n_envs
        space = self._env.single_observation_space
        self.obs_dim = int(np.prod(space.shape))
        self.n_actions = int(self._env.single_action_space.n)
        self._seed = seed
        spec = getattr(self._env, "spec", None)
        self.max_episode_steps = getattr(spec, "max_episode_steps", 500) or 500

    def reset(self) -> np.ndarray:
        obs, _ = self._env.reset(seed=self._seed)
        return obs.reshape(self.n_envs, -1).astype(np.float32)

    def step(self, actions: np.ndarray):
        obs, rewards, terminated, truncated, infos = self._env.step(actions)
        terminated = np.asarray(terminated)
        truncated = np.asarray(truncated) & ~terminated  # termination wins
        dones = terminated | truncated
        obs = obs.reshape(self.n_envs, -1).astype(np.float32)
        out_infos = {"truncated": truncated}
        if dones.any():
            # Gymnasium SAME_STEP autoreset reports the pre-reset
            # observation per done env (key name varies across versions);
            # default to the returned obs where absent. Built only on steps
            # with an episode end — the hot loop stays allocation-lean.
            final_obs = obs.copy()
            raw_final = infos.get("final_obs",
                                  infos.get("final_observation"))
            if raw_final is not None:
                for i in np.nonzero(dones)[0]:
                    fo = raw_final[i]
                    if fo is not None:
                        final_obs[i] = np.asarray(fo, np.float32).reshape(-1)
            out_infos["final_obs"] = final_obs
        return (obs, np.asarray(rewards, dtype=np.float32), dones, out_infos)


def make_env(env: Any, n_envs: int, seed: int = 0) -> VectorEnv:
    """env may be a VectorEnv factory, a VectorEnv, or a gymnasium id."""
    if isinstance(env, VectorEnv):
        return env
    if callable(env):
        out = env(n_envs=n_envs, seed=seed)
        assert isinstance(out, VectorEnv)
        return out
    if env in ("CartPole-v1", "CartPole"):
        return CartPoleVectorEnv(n_envs=n_envs, seed=seed)
    return GymnasiumVectorEnv(env, n_envs=n_envs, seed=seed)
